"""Decoder-only transformer — the long-context model family.

Not present in the reference (trtlab predates LLM serving — SURVEY §2.8 scope
note); included because the TPU build treats long-context/sequence scaling as
first-class.  The attention op is pluggable so the parallel layer can swap in
ring attention (:mod:`tpulab.parallel.ring_attention`) for sequence lengths
that exceed one chip's HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def init_transformer_params(vocab: int = 32000, d_model: int = 512,
                            n_heads: int = 8, n_layers: int = 6,
                            d_ff: int = 2048, seed: int = 0,
                            n_kv_heads: Optional[int] = None,
                            ffn: str = "gelu",
                            tie_embeddings: bool = True) -> Dict[str, Any]:
    """``n_kv_heads < n_heads`` selects grouped-query attention (GQA;
    ``n_kv_heads=1`` is MQA): K/V projections shrink to ``n_kv_heads``
    heads, cutting KV-cache HBM and decode bandwidth by the group factor.
    Default (None) is standard multi-head attention.  ``ffn="swiglu"``
    adds the w3 gate projection (Llama family); ``tie_embeddings=False``
    adds an untied ``lm_head``."""
    n_kv = n_kv_heads or n_heads
    if n_heads % n_kv:
        raise ValueError(f"n_heads {n_heads} not divisible by "
                         f"n_kv_heads {n_kv}")
    head_dim = d_model // n_heads
    rng = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(rng, 4 * n_layers + 4))
    s = 0.02
    params: Dict[str, Any] = {
        "embed": jax.random.normal(next(keys), (vocab, d_model)) * s,
        "final_norm": {"scale": jnp.ones((d_model,))},
    }
    for i in range(n_layers):
        lkeys = iter(jax.random.split(next(keys), 8))
        params[f"layer{i}"] = {
            "ln1": {"scale": jnp.ones((d_model,))},
            "ln2": {"scale": jnp.ones((d_model,))},
            "wqkv": jax.random.normal(
                next(lkeys),
                (d_model, (n_heads + 2 * n_kv) * head_dim)) * s,
            "wo": jax.random.normal(next(lkeys), (d_model, d_model)) * s,
            "w1": jax.random.normal(next(lkeys), (d_model, d_ff)) * s,
            "w2": jax.random.normal(next(lkeys), (d_ff, d_model)) * s,
        }
        if ffn == "swiglu":
            params[f"layer{i}"]["w3"] = jax.random.normal(
                next(lkeys), (d_model, d_ff)) * s
        elif ffn != "gelu":
            raise ValueError(f"unknown ffn {ffn!r}")
    if not tie_embeddings:
        params["lm_head"] = jax.random.normal(next(keys),
                                              (d_model, vocab)) * s
    return params


def split_qkv(qkv, b, t, n_heads, n_kv_heads, head_dim):
    """Split a fused QKV projection into (q (B,T,Hq,D), k/v (B,T,Hkv,D))."""
    q_dim = n_heads * head_dim
    kv_dim = n_kv_heads * head_dim
    q = qkv[..., :q_dim].reshape(b, t, n_heads, head_dim)
    k = qkv[..., q_dim:q_dim + kv_dim].reshape(b, t, n_kv_heads, head_dim)
    v = qkv[..., q_dim + kv_dim:].reshape(b, t, n_kv_heads, head_dim)
    return q, k, v


def repeat_kv(kv, n_heads):
    """Broadcast (…, Hkv, D) K/V heads up to the query head count (GQA)."""
    hkv = kv.shape[-2]
    if hkv == n_heads:
        return kv
    return jnp.repeat(kv, n_heads // hkv, axis=-2)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotary position embedding (HF Llama rotate-half convention).

    x (..., T, H, D); positions (..., T) int — broadcast against x's batch
    dims.  K is rotated BEFORE cache/pool writes, so cached keys are
    position-baked and attention needs no further rotation.
    """
    d = x.shape[-1]
    half = d // 2
    inv = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions.astype(jnp.float32)[..., None] * inv   # (..., T, half)
    cos = jnp.concatenate([jnp.cos(ang)] * 2, -1)[..., None, :]  # (.., T, 1, D)
    sin = jnp.concatenate([jnp.sin(ang)] * 2, -1)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos + rot * sin).astype(x.dtype)


def qmat(w, compute_dtype):
    """Weight matrix ready for matmul, transparently dequantizing
    weight-only INT8 entries ({"w_int8", "scale"} from
    :func:`tpulab.models.quantization.quantize_transformer_params`).

    TPU-first W8A16: the int8 matrix is what lives in (and streams from)
    HBM — the 2-4x smaller read is the win, since small-batch decode is
    weight-bandwidth-bound; the cast and per-output-channel scale are
    cheap VPU work XLA fuses into the consuming matmul's operand read.
    """
    if isinstance(w, dict) and "w_int8" in w:
        return (w["w_int8"].astype(compute_dtype)
                * w["scale"].astype(compute_dtype))
    return w.astype(compute_dtype)


def weight_shape(w):
    """Shape of a (possibly weight-only-quantized) weight matrix."""
    return (w["w_int8"] if isinstance(w, dict) and "w_int8" in w
            else w).shape


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def dense_attention(q, k, v, causal: bool = True):
    """Single-device attention (B, T, H, D), optionally causal."""
    b, t, h, d = q.shape
    scores = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def causal_attention(q, k, v):
    """Default single-device causal attention (B, T, H, D)."""
    return dense_attention(q, k, v, causal=True)


def _dense_ffn(p, h, compute_dtype):
    """Default FFN block: SwiGLU when the layer has a ``w3`` gate
    projection (the Llama family), else w1/gelu/w2."""
    if "w3" in p:
        return (jax.nn.silu(h @ qmat(p["w1"], compute_dtype))
                * (h @ qmat(p["w3"], compute_dtype))) \
            @ qmat(p["w2"], compute_dtype)
    return jax.nn.gelu(h @ qmat(p["w1"], compute_dtype)) \
        @ qmat(p["w2"], compute_dtype)


def _lm_head(params, x):
    """Final projection: untied ``lm_head`` when present, else tied to the
    embedding matrix."""
    if "lm_head" in params:
        return x.astype(jnp.float32) @ qmat(params["lm_head"], jnp.float32)
    return x.astype(jnp.float32) @ params["embed"].T.astype(jnp.float32)


def _forward(params, tokens, n_heads, n_layers, compute_dtype, attention_fn,
             collect_kv: bool = False, ffn_fn=_dense_ffn,
             n_kv_heads: Optional[int] = None,
             rope_theta: Optional[float] = None):
    """Shared transformer trunk: (B, T) tokens -> (logits, kvs or None).
    ``ffn_fn(layer_params, h, compute_dtype)`` swaps the FFN (dense / MoE).
    ``collect_kv`` returns the UNexpanded (B, T, Hkv, D) heads — the
    compact form KV caches/pools store under GQA.  ``rope_theta`` enables
    rotary embeddings at absolute positions 0..T-1 (collected K is rotated,
    matching the decode paths' position-baked caches).  Under sequence
    parallelism pass pre-roped inputs or keep rope off here."""
    n_kv = n_kv_heads or n_heads
    emb = params["embed"].astype(compute_dtype)
    x = emb[tokens]
    b, t, d_model = x.shape
    head_dim = d_model // n_heads
    kvs = [] if collect_kv else None
    positions = jnp.arange(t) if rope_theta else None
    for i in range(n_layers):
        p = params[f"layer{i}"]
        h = _rmsnorm(x, p["ln1"]["scale"])
        qkv = h @ qmat(p["wqkv"], compute_dtype)
        q, k, v = split_qkv(qkv, b, t, n_heads, n_kv, head_dim)
        if rope_theta:
            q = apply_rope(q, positions, rope_theta)
            k = apply_rope(k, positions, rope_theta)
        if collect_kv:
            kvs.append((k, v))
        attn = attention_fn(q, repeat_kv(k, n_heads),
                            repeat_kv(v, n_heads)).reshape(b, t, d_model)
        x = x + attn @ qmat(p["wo"], compute_dtype)
        h = _rmsnorm(x, p["ln2"]["scale"])
        x = x + ffn_fn(p, h, compute_dtype).astype(x.dtype)
    x = _rmsnorm(x, params["final_norm"]["scale"])
    return _lm_head(params, x), kvs


def transformer_apply(params: Dict[str, Any], inputs: Dict[str, jnp.ndarray],
                      n_heads: int = 8, n_layers: int = 6,
                      compute_dtype=jnp.bfloat16,
                      attention_fn: Callable = causal_attention,
                      n_kv_heads: Optional[int] = None,
                      rope_theta: Optional[float] = None
                      ) -> Dict[str, jnp.ndarray]:
    """tokens (B, T) int32 -> logits (B, T, vocab) f32."""
    logits, _ = _forward(params, inputs["tokens"], n_heads, n_layers,
                         compute_dtype, attention_fn,
                         n_kv_heads=n_kv_heads, rope_theta=rope_theta)
    return {"logits": logits}


def make_transformer(vocab: int = 32000, d_model: int = 512, n_heads: int = 8,
                     n_layers: int = 6, d_ff: int = 2048, seq_len: int = 1024,
                     max_batch_size: int = 4, compute_dtype=jnp.bfloat16,
                     seed: int = 0, attention_fn: Callable = causal_attention,
                     n_kv_heads: Optional[int] = None):
    from tpulab.engine.model import IOSpec, Model

    params = init_transformer_params(vocab, d_model, n_heads, n_layers,
                                     d_ff, seed, n_kv_heads=n_kv_heads)
    apply_fn = partial(transformer_apply, n_heads=n_heads, n_layers=n_layers,
                       compute_dtype=compute_dtype, attention_fn=attention_fn,
                       n_kv_heads=n_kv_heads)
    return Model(
        name="transformer",
        apply_fn=apply_fn,
        params=params,
        inputs=[IOSpec("tokens", (seq_len,), np.int32)],
        outputs=[IOSpec("logits", (seq_len, vocab), np.float32)],
        max_batch_size=max_batch_size,
    )


# ---------------------------------------------------------------------------
# KV-cache decode (autoregressive serving)
# ---------------------------------------------------------------------------

def init_kv_cache(batch: int, max_len: int, n_layers: int, n_heads: int,
                  head_dim: int, dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Preallocated per-layer K/V rings (B, T_max, H, Dh) — pass the KV
    head count here (``n_kv_heads`` under GQA)."""
    shape = (batch, max_len, n_heads, head_dim)
    return {f"layer{i}": {"k": jnp.zeros(shape, dtype),
                          "v": jnp.zeros(shape, dtype)}
            for i in range(n_layers)}


def transformer_decode_step(params: Dict[str, Any], cache: Dict[str, Any],
                            tokens: jnp.ndarray, pos: jnp.ndarray,
                            n_heads: int = 8, n_layers: int = 6,
                            compute_dtype=jnp.bfloat16,
                            n_kv_heads: Optional[int] = None,
                            rope_theta: Optional[float] = None):
    """One decode step: tokens (B,) int32 at position ``pos`` (scalar int32).

    Returns (logits (B, vocab) f32, updated cache).  Attention runs against
    cache[: pos+1] via position masking — static shapes, scan/jit friendly
    (no data-dependent Python control flow).  Exactly the M=1 case of
    :func:`transformer_chunk_step` (single source of truth for the
    cache-attention math).
    """
    logits, new_cache = transformer_chunk_step(
        params, cache, tokens[:, None], jnp.asarray(pos),
        n_heads=n_heads, n_layers=n_layers, compute_dtype=compute_dtype,
        n_kv_heads=n_kv_heads, rope_theta=rope_theta)
    return logits[:, 0], new_cache


def transformer_chunk_step(params: Dict[str, Any], cache: Dict[str, Any],
                           tokens: jnp.ndarray, pos0: jnp.ndarray,
                           n_heads: int = 8, n_layers: int = 6,
                           compute_dtype=jnp.bfloat16,
                           n_kv_heads: Optional[int] = None,
                           rope_theta: Optional[float] = None):
    """Multi-token decode: process M new tokens (B, M) starting at position
    ``pos0`` (scalar int32) against the KV cache in ONE forward.

    Attention per chunk token m: all cache positions < pos0 + causal within
    the chunk.  Returns (logits (B, M, vocab) f32, updated cache).  This is
    the chunked-prefill AND speculative-verify primitive: a chunk of draft
    proposals verifies in one pass, and cache entries written past an
    eventual acceptance point are harmless — positions only advance, so
    stale slots are overwritten before they can ever be attended to.
    """
    n_kv = n_kv_heads or n_heads
    emb = params["embed"].astype(compute_dtype)
    x = emb[tokens]                                  # (B, M, D)
    b, m, d_model = x.shape
    head_dim = d_model // n_heads
    max_len = next(iter(cache.values()))["k"].shape[1]
    positions = pos0 + jnp.arange(m) if rope_theta else None
    new_cache = {}
    for i in range(n_layers):
        p = params[f"layer{i}"]
        h = _rmsnorm(x, p["ln1"]["scale"])
        qkv = h @ qmat(p["wqkv"], compute_dtype)
        q, k, v = split_qkv(qkv, b, m, n_heads, n_kv, head_dim)
        if rope_theta:
            q = apply_rope(q, positions, rope_theta)
            k = apply_rope(k, positions, rope_theta)
        ck = jax.lax.dynamic_update_slice(
            cache[f"layer{i}"]["k"], k.astype(cache[f"layer{i}"]["k"].dtype),
            (0, pos0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cache[f"layer{i}"]["v"], v.astype(cache[f"layer{i}"]["v"].dtype),
            (0, pos0, 0, 0))
        new_cache[f"layer{i}"] = {"k": ck, "v": cv}
        # mask: chunk token m attends cache position j iff j <= pos0 + m
        g = n_heads // n_kv
        qg = q.reshape(b, m, n_kv, g, head_dim)
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                            ck.astype(jnp.float32)) / np.sqrt(head_dim)
        k_pos = jnp.arange(max_len)
        vis = k_pos[None, :] <= (pos0 + jnp.arange(m))[:, None]   # (M, T)
        scores = jnp.where(vis[None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(compute_dtype)
        attn = jnp.einsum("bhgqk,bkhd->bqhgd", probs,
                          cv.astype(compute_dtype)).reshape(b, m, d_model)
        x = x + attn @ qmat(p["wo"], compute_dtype)
        h2 = _rmsnorm(x, p["ln2"]["scale"])
        x = x + _dense_ffn(p, h2, compute_dtype).astype(x.dtype)
    x = _rmsnorm(x, params["final_norm"]["scale"])
    return _lm_head(params, x), new_cache


def make_generate_fn(params: Dict[str, Any], n_heads: int, n_layers: int,
                     max_len: int, compute_dtype=jnp.bfloat16,
                     n_kv_heads: Optional[int] = None,
                     rope_theta: Optional[float] = None):
    """Jitted greedy generation: (prompt (B, T_p), steps) -> (B, steps).

    Prefill replays the prompt through scanned decode steps to warm the
    cache (a fused batched-prefill that writes the cache directly is the
    next optimization); decode is a lax.scan of cached steps —
    compiler-friendly: no growing shapes, no recompiles per step.
    """

    n_kv = n_kv_heads or n_heads

    def generate(prompt: jnp.ndarray, steps: int):
        b, t_p = prompt.shape
        head_dim = params["embed"].shape[1] // n_heads
        cache = init_kv_cache(b, max_len, n_layers, n_kv, head_dim,
                              compute_dtype)
        # prefill: run the full forward for logits, then replay the prompt
        # through decode steps to warm the cache (simple, correct; a fused
        # prefill that writes the cache directly is the next optimization)
        def prefill_body(carry, i):
            cache, _ = carry
            logits, cache = transformer_decode_step(
                params, cache, prompt[:, i], i, n_heads, n_layers,
                compute_dtype, n_kv_heads=n_kv, rope_theta=rope_theta)
            return (cache, logits), None

        (cache, logits), _ = jax.lax.scan(
            prefill_body, (cache, jnp.zeros((b, params["embed"].shape[0]))),
            jnp.arange(t_p))

        def decode_body(carry, i):
            cache, tok = carry
            logits, cache = transformer_decode_step(
                params, cache, tok, t_p + i, n_heads, n_layers,
                compute_dtype, n_kv_heads=n_kv, rope_theta=rope_theta)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, nxt), nxt

        first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        (_, _), toks = jax.lax.scan(decode_body, (cache, first),
                                    jnp.arange(steps - 1))
        return jnp.concatenate([first[:, None], toks.T], axis=1)

    return jax.jit(generate, static_argnums=1)


def transformer_forward_collect_kv(params: Dict[str, Any],
                                   tokens: jnp.ndarray,
                                   n_heads: int = 8, n_layers: int = 6,
                                   compute_dtype=jnp.bfloat16,
                                   attention_fn: Callable = causal_attention,
                                   n_kv_heads: Optional[int] = None,
                                   rope_theta: Optional[float] = None):
    """Causal forward over (B, T) tokens that also returns each layer's
    K/V (B, T, Hkv, Dh) — the fused-prefill building block: one forward
    fills a whole prompt's KV instead of T decode steps.  Shares the trunk
    with :func:`transformer_apply` (single source of truth)."""
    return _forward(params, tokens, n_heads, n_layers, compute_dtype,
                    attention_fn, collect_kv=True, n_kv_heads=n_kv_heads,
                    rope_theta=rope_theta)


def early_exit_draft(target_params: Dict[str, Any],
                     draft_layers: int) -> Dict[str, Any]:
    """Self-speculative draft: the target's first ``draft_layers`` layers
    + its embed/final-norm/lm-head — 'early-exit' drafting (LayerSkip /
    Draft-&-Verify family).  No second model to train or ship: the draft
    IS a prefix of the target, so acceptance measures real early-exit
    agreement rather than a synthetic twin.

    The returned tree SHARES the target's weight arrays (no copy, no
    extra HBM beyond what the target already holds) and, by
    construction, the target's head geometry (head_dim, n_kv_heads) —
    exactly what the paged speculative path requires, since the draft's
    KV rides the target's :class:`~tpulab.engine.paged.PagedKVPool`
    through a second page table (``ContinuousBatcher(draft_params=...,
    draft_n_layers=...)``).  The dense
    :class:`~tpulab.engine.speculative.SpeculativeGenerator` takes the
    same tree."""
    p = {"embed": target_params["embed"],
         "final_norm": target_params["final_norm"]}
    if "lm_head" in target_params:
        p["lm_head"] = target_params["lm_head"]
    for i in range(draft_layers):
        p[f"layer{i}"] = target_params[f"layer{i}"]
    return p


def make_moe_transformer(vocab: int = 32000, d_model: int = 512,
                         n_heads: int = 8, n_layers: int = 6,
                         d_ff: int = 2048, n_experts: int = 8,
                         top_k: int = 2, seq_len: int = 1024,
                         max_batch_size: int = 4,
                         compute_dtype=jnp.bfloat16, seed: int = 0,
                         attention_fn: Callable = causal_attention):
    """Transformer with MoE FFN blocks (per-layer expert banks; dense
    compute here, expert-parallel execution via
    tpulab.parallel.moe.make_expert_parallel_ffn over the same params)."""
    from tpulab.engine.model import IOSpec, Model
    from tpulab.parallel.moe import init_moe_params, moe_ffn

    rng = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(rng, 2 * n_layers + 2))
    s = 0.02
    params: Dict[str, Any] = {
        "embed": jax.random.normal(next(keys), (vocab, d_model)) * s,
        "final_norm": {"scale": jnp.ones((d_model,))},
    }
    for i in range(n_layers):
        params[f"layer{i}"] = {
            "ln1": {"scale": jnp.ones((d_model,))},
            "ln2": {"scale": jnp.ones((d_model,))},
            "wqkv": jax.random.normal(next(keys), (d_model, 3 * d_model)) * s,
            "wo": jax.random.normal(next(keys), (d_model, d_model)) * s,
            "moe": init_moe_params(d_model, d_ff, n_experts,
                                   seed=seed + i + 1),
        }

    def moe_block(lp, h, cdtype):
        b, t, dm = h.shape
        return moe_ffn(lp["moe"], h.reshape(b * t, dm), top_k=top_k,
                       compute_dtype=cdtype).reshape(b, t, dm)

    def apply_fn(p, inputs):
        logits, _ = _forward(p, inputs["tokens"], n_heads, n_layers,
                             compute_dtype, attention_fn, ffn_fn=moe_block)
        return {"logits": logits}

    return Model(
        name="moe_transformer",
        apply_fn=apply_fn,
        params=params,
        inputs=[IOSpec("tokens", (seq_len,), np.int32)],
        outputs=[IOSpec("logits", (seq_len, vocab), np.float32)],
        max_batch_size=max_batch_size,
    )
