"""MNIST convnet (reference models/onnx/mnist-v1.3 — the quickstart/test
model with golden test vectors)."""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


def init_mnist_params(seed: int = 0) -> Dict[str, Any]:
    k = jax.random.split(jax.random.PRNGKey(seed), 4)
    return {
        "conv1": {"kernel": jax.random.normal(k[0], (5, 5, 1, 8)) * 0.1,
                  "bias": jnp.zeros((8,))},
        "conv2": {"kernel": jax.random.normal(k[1], (5, 5, 8, 16)) * 0.1,
                  "bias": jnp.zeros((16,))},
        "fc": {"kernel": jax.random.normal(k[2], (7 * 7 * 16, 10)) * 0.05,
               "bias": jnp.zeros((10,))},
    }


def mnist_apply(params: Dict[str, Any],
                inputs: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """NHWC 28x28x1 image -> 10 logits (binding names mirror the onnx model:
    Input3 -> Plus214_Output_0, reference pybind infer.cc MNIST usage)."""
    x = inputs["Input3"]
    maxpool = partial(jax.lax.reduce_window, init_value=-jnp.inf,
                      computation=jax.lax.max,
                      window_dimensions=(1, 2, 2, 1),
                      window_strides=(1, 2, 2, 1),
                      padding=[(0, 0), (0, 0), (0, 0), (0, 0)])
    for layer in ("conv1", "conv2"):
        x = jax.lax.conv_general_dilated(
            x, params[layer]["kernel"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + params[layer]["bias"])
        x = maxpool(x)
    x = x.reshape((x.shape[0], -1))
    logits = x @ params["fc"]["kernel"] + params["fc"]["bias"]
    return {"Plus214_Output_0": logits}


def make_mnist(max_batch_size: int = 8, seed: int = 0):
    from tpulab.engine.model import IOSpec, Model

    return Model(
        name="mnist",
        apply_fn=mnist_apply,
        params=init_mnist_params(seed),
        inputs=[IOSpec("Input3", (28, 28, 1), np.float32)],
        outputs=[IOSpec("Plus214_Output_0", (10,), np.float32)],
        max_batch_size=max_batch_size,
    )
