"""tpulab.models — the model zoo (reference models/ + examples/ONNX: ResNet-50
/152 and MNIST engine-building assets, SURVEY §2.7).

Models are defined in Flax and materialize as :class:`tpulab.engine.Model`
objects via builders in :mod:`registry`; the engine layer compiles them per
batch bucket.  bf16 compute is the default on TPU (MXU-native), float32 I/O at
the binding boundary.
"""

from tpulab.models.registry import build_model, available_models

__all__ = ["build_model", "available_models"]
