"""tpulab.models — the model zoo (reference models/ + examples/ONNX: ResNet-50
/152 and MNIST engine-building assets, SURVEY §2.7).

Models are defined in Flax and materialize as :class:`tpulab.engine.Model`
objects via builders in :mod:`registry`; the engine layer compiles them per
batch bucket.  bf16 compute is the default on TPU (MXU-native), float32 I/O at
the binding boundary.
"""

from tpulab.models.registry import build_model, available_models

__all__ = ["build_model", "available_models", "early_exit_draft"]


def __getattr__(name):
    # lazy: tpulab.models.early_exit_draft (the draft-param plumbing for
    # speculative decoding) without importing jax at package import time
    if name == "early_exit_draft":
        from tpulab.models.transformer import early_exit_draft
        return early_exit_draft
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
