"""Import pretrained torch ResNet weights into tpulab's jax ResNet.

Engine-building tooling parity (reference examples/ONNX/resnet50/build.py +
models/onnx_builder.py build real engines from model-zoo artifacts): this
maps a torchvision-layout ``state_dict`` (``conv1.weight``,
``layer{1-4}.{b}.conv{1-3}.weight``, ``bn*`` stats, ``fc.*``) onto
:func:`tpulab.models.resnet.init_resnet_params`' layout, folding each
BatchNorm into the conv's scale/bias:

    scale = gamma / sqrt(var + eps);  bias = beta - mean * scale

so the serving graph stays the folded conv+scale+bias form.  Weights convert
OIHW -> HWIO (NHWC serving layout).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

STAGE_BLOCKS = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
BN_EPS = 1e-5


def _fold_bn(sd: Mapping[str, Any], conv_key: str, bn_key: str) -> Dict[str, np.ndarray]:
    w = np.asarray(sd[f"{conv_key}.weight"], np.float32)      # OIHW
    gamma = np.asarray(sd[f"{bn_key}.weight"], np.float32)
    beta = np.asarray(sd[f"{bn_key}.bias"], np.float32)
    mean = np.asarray(sd[f"{bn_key}.running_mean"], np.float32)
    var = np.asarray(sd[f"{bn_key}.running_var"], np.float32)
    scale = gamma / np.sqrt(var + BN_EPS)
    bias = beta - mean * scale
    return {
        "kernel": np.transpose(w, (2, 3, 1, 0)),              # -> HWIO
        "scale": scale,
        "bias": bias,
    }


def resnet_params_from_torch(state_dict: Mapping[str, Any],
                             depth: int = 50) -> Dict[str, Any]:
    """torchvision ResNet state_dict -> tpulab resnet params pytree."""
    if depth not in STAGE_BLOCKS:
        raise ValueError(f"unsupported depth {depth}")
    sd = state_dict
    params: Dict[str, Any] = {"stem": _fold_bn(sd, "conv1", "bn1")}
    for stage, blocks in enumerate(STAGE_BLOCKS[depth]):
        for block in range(blocks):
            prefix = f"layer{stage + 1}.{block}"
            p = {
                "conv1": _fold_bn(sd, f"{prefix}.conv1", f"{prefix}.bn1"),
                "conv2": _fold_bn(sd, f"{prefix}.conv2", f"{prefix}.bn2"),
                "conv3": _fold_bn(sd, f"{prefix}.conv3", f"{prefix}.bn3"),
            }
            if f"{prefix}.downsample.0.weight" in sd:
                p["proj"] = _fold_bn(sd, f"{prefix}.downsample.0",
                                     f"{prefix}.downsample.1")
            params[f"s{stage}b{block}"] = p
    params["fc"] = {
        "kernel": np.asarray(sd["fc.weight"], np.float32).T,
        "bias": np.asarray(sd["fc.bias"], np.float32),
    }
    return params


def make_resnet_from_torch(state_dict_or_path, depth: int = 50,
                           **make_kwargs):
    """Build a servable Model from a torch checkpoint (path or state_dict)."""
    if isinstance(state_dict_or_path, (str, bytes)):
        import torch
        state_dict = torch.load(state_dict_or_path, map_location="cpu",
                                weights_only=True)
    else:
        state_dict = state_dict_or_path
    if hasattr(next(iter(state_dict.values())), "detach"):
        state_dict = {k: v.detach().cpu().numpy()
                      for k, v in state_dict.items()}
    from tpulab.models.resnet import make_resnet
    model = make_resnet(depth=depth, **make_kwargs)
    model.params = resnet_params_from_torch(state_dict, depth)
    return model


# --------------------------------------------------------------------------
# Llama-family import (HF LlamaForCausalLM state_dict -> tpulab transformer)
# --------------------------------------------------------------------------

def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        # .float() first: torch bf16 tensors (how Llama checkpoints ship)
        # have no direct .numpy() path
        return t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def llama_params_from_torch(state_dict: Mapping[str, Any],
                            n_layers: int = 0) -> Dict[str, Any]:
    """HF ``LlamaForCausalLM`` state_dict -> tpulab transformer params.

    Maps the Llama architecture onto this framework's transformer family
    (RMSNorm + RoPE + GQA + SwiGLU, all of which the family implements
    natively): q/k/v projections fuse into ``wqkv`` (torch Linear weights
    are (out, in) — transposed to the (in, out) matmul layout used here),
    gate/up/down become w1/w3/w2, and an untied ``lm_head`` is imported
    when present (tied models fall back to the embedding transpose).

    Serve the result with ``n_kv_heads`` and ``rope_theta`` from the HF
    config (e.g. ``ContinuousBatcher(params, n_heads=cfg.num_attention_heads,
    n_kv_heads=cfg.num_key_value_heads, rope_theta=cfg.rope_theta, ...)``).
    """
    sd = state_dict
    ckpt_layers = len({k.split(".")[2] for k in sd
                       if k.startswith("model.layers.")})
    if n_layers == 0:
        n_layers = ckpt_layers
    elif n_layers != ckpt_layers:
        raise ValueError(f"n_layers={n_layers} but the checkpoint has "
                         f"{ckpt_layers} decoder layers")
    params: Dict[str, Any] = {
        "embed": _np(sd["model.embed_tokens.weight"]),
        "final_norm": {"scale": _np(sd["model.norm.weight"])},
    }
    for i in range(n_layers):
        pre = f"model.layers.{i}"
        wq = _np(sd[f"{pre}.self_attn.q_proj.weight"]).T     # (in, Hq*D)
        wk = _np(sd[f"{pre}.self_attn.k_proj.weight"]).T     # (in, Hkv*D)
        wv = _np(sd[f"{pre}.self_attn.v_proj.weight"]).T
        params[f"layer{i}"] = {
            "ln1": {"scale": _np(sd[f"{pre}.input_layernorm.weight"])},
            "ln2": {"scale": _np(
                sd[f"{pre}.post_attention_layernorm.weight"])},
            "wqkv": np.concatenate([wq, wk, wv], axis=1),
            "wo": _np(sd[f"{pre}.self_attn.o_proj.weight"]).T,
            "w1": _np(sd[f"{pre}.mlp.gate_proj.weight"]).T,
            "w3": _np(sd[f"{pre}.mlp.up_proj.weight"]).T,
            "w2": _np(sd[f"{pre}.mlp.down_proj.weight"]).T,
        }
    if "lm_head.weight" in sd:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    # jnp leaves: numpy leaves can't be indexed by traced token ids
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(jnp.asarray, params)


# --------------------------------------------------------------------------
# ViT import (HF ViTForImageClassification state_dict -> tpulab vit)
# --------------------------------------------------------------------------

def vit_params_from_hf(state_dict: Mapping[str, Any],
                       layer_norm_eps: float = 1e-12,
                       image_mean=(0.5, 0.5, 0.5),
                       image_std=(0.5, 0.5, 0.5)) -> Dict[str, Any]:
    """HF ``ViTForImageClassification`` state_dict -> tpulab vit params.

    The in-house ViT is RMSNorm/bias-free (TPU-first defaults); imported
    checkpoints keep their classic dialect faithfully — LayerNorm with
    bias (+ the config's eps), biased projections, exact erf-gelu — all
    selected inside :func:`tpulab.models.vit.vit_apply` by the presence
    of the bias entries this importer writes.  The patch-embedding
    Conv2d(C, D, p, stride=p) becomes the patchify matmul's
    (p*p*C, D) weight (kernel transposed (kh, kw, C) -> row order,
    matching vit_apply's (p_h, p_w, c) patch flattening).
    """
    sd = state_dict
    n_layers = len({k.split(".")[3] for k in sd
                    if k.startswith("vit.encoder.layer.")})
    proj = _np(sd["vit.embeddings.patch_embeddings.projection.weight"])
    eps = np.float32(layer_norm_eps)
    params: Dict[str, Any] = {
        "cls": _np(sd["vit.embeddings.cls_token"]).reshape(-1),
        "pos_embed": _np(sd["vit.embeddings.position_embeddings"])[0],
        # (D, C, p, p) -> (p, p, C, D) -> (p*p*C, D)
        "patch_embed": np.transpose(proj, (2, 3, 1, 0)).reshape(
            -1, proj.shape[0]),
        "patch_bias": _np(
            sd["vit.embeddings.patch_embeddings.projection.bias"]),
        "final_norm": {"scale": _np(sd["vit.layernorm.weight"]),
                       "bias": _np(sd["vit.layernorm.bias"]),
                       "eps": eps},
        "head": {"kernel": _np(sd["classifier.weight"]).T,
                 "bias": _np(sd["classifier.bias"])},
        # uint8-ingress normalization: the checkpoint PROCESSOR's stats
        # (HF ViT default mean/std = 0.5), NOT the imagenet defaults
        "norm_mean": np.asarray(image_mean, np.float32),
        "norm_std": np.asarray(image_std, np.float32),
    }
    for i in range(n_layers):
        pre = f"vit.encoder.layer.{i}"
        att = f"{pre}.attention.attention"
        params[f"layer{i}"] = {
            "ln1": {"scale": _np(sd[f"{pre}.layernorm_before.weight"]),
                    "bias": _np(sd[f"{pre}.layernorm_before.bias"]),
                    "eps": eps},
            "ln2": {"scale": _np(sd[f"{pre}.layernorm_after.weight"]),
                    "bias": _np(sd[f"{pre}.layernorm_after.bias"]),
                    "eps": eps},
            "wqkv": np.concatenate(
                [_np(sd[f"{att}.{n}.weight"]).T
                 for n in ("query", "key", "value")], axis=1),
            "bqkv": np.concatenate(
                [_np(sd[f"{att}.{n}.bias"])
                 for n in ("query", "key", "value")]),
            "wo": _np(sd[f"{pre}.attention.output.dense.weight"]).T,
            "bo": _np(sd[f"{pre}.attention.output.dense.bias"]),
            "w1": _np(sd[f"{pre}.intermediate.dense.weight"]).T,
            "b1": _np(sd[f"{pre}.intermediate.dense.bias"]),
            "w2": _np(sd[f"{pre}.output.dense.weight"]).T,
            "b2": _np(sd[f"{pre}.output.dense.bias"]),
        }
    return params


def make_vit_from_hf(state_dict_or_path, *, image_size: int,
                     patch_size: int, n_heads: int,
                     layer_norm_eps: float = 1e-12, **make_kwargs):
    """Servable ViT from an HF checkpoint (path or state_dict).  Geometry
    (image/patch/heads) comes from the HF config — pass it explicitly,
    like :func:`llama_params_from_torch`'s serve-time contract."""
    if isinstance(state_dict_or_path, (str, bytes)):
        import torch
        state_dict = torch.load(state_dict_or_path, map_location="cpu",
                                weights_only=True)
    else:
        state_dict = state_dict_or_path
    params = vit_params_from_hf(state_dict, layer_norm_eps)
    n_layers = len([k for k in params if k.startswith("layer")])
    d_model = params["patch_embed"].shape[1]
    num_classes = params["head"]["bias"].shape[0]

    from functools import partial

    from tpulab.engine.model import IOSpec, Model
    from tpulab.models.vit import vit_apply
    import jax.numpy as jnp

    apply_fn = partial(vit_apply, n_heads=n_heads, n_layers=n_layers,
                       patch_size=patch_size,
                       compute_dtype=make_kwargs.pop("compute_dtype",
                                                     jnp.bfloat16))
    expect = (image_size // patch_size) ** 2 + 1
    if params["pos_embed"].shape[0] != expect:
        raise ValueError(
            f"image_size {image_size}/patch {patch_size} implies "
            f"{expect} positions but the checkpoint has "
            f"{params['pos_embed'].shape[0]}")
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} not divisible by "
                         f"n_heads {n_heads}")
    return Model(
        name=make_kwargs.pop("name", f"vit_hf_{patch_size}"),
        apply_fn=apply_fn, params=params,
        inputs=[IOSpec("input", (image_size, image_size, 3),
                       make_kwargs.pop("input_dtype", np.float32))],
        outputs=[IOSpec("logits", (num_classes,), np.float32)],
        **make_kwargs)
