"""Import pretrained torch ResNet weights into tpulab's jax ResNet.

Engine-building tooling parity (reference examples/ONNX/resnet50/build.py +
models/onnx_builder.py build real engines from model-zoo artifacts): this
maps a torchvision-layout ``state_dict`` (``conv1.weight``,
``layer{1-4}.{b}.conv{1-3}.weight``, ``bn*`` stats, ``fc.*``) onto
:func:`tpulab.models.resnet.init_resnet_params`' layout, folding each
BatchNorm into the conv's scale/bias:

    scale = gamma / sqrt(var + eps);  bias = beta - mean * scale

so the serving graph stays the folded conv+scale+bias form.  Weights convert
OIHW -> HWIO (NHWC serving layout).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

STAGE_BLOCKS = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
BN_EPS = 1e-5


def _fold_bn(sd: Mapping[str, Any], conv_key: str, bn_key: str) -> Dict[str, np.ndarray]:
    w = np.asarray(sd[f"{conv_key}.weight"], np.float32)      # OIHW
    gamma = np.asarray(sd[f"{bn_key}.weight"], np.float32)
    beta = np.asarray(sd[f"{bn_key}.bias"], np.float32)
    mean = np.asarray(sd[f"{bn_key}.running_mean"], np.float32)
    var = np.asarray(sd[f"{bn_key}.running_var"], np.float32)
    scale = gamma / np.sqrt(var + BN_EPS)
    bias = beta - mean * scale
    return {
        "kernel": np.transpose(w, (2, 3, 1, 0)),              # -> HWIO
        "scale": scale,
        "bias": bias,
    }


def resnet_params_from_torch(state_dict: Mapping[str, Any],
                             depth: int = 50) -> Dict[str, Any]:
    """torchvision ResNet state_dict -> tpulab resnet params pytree."""
    if depth not in STAGE_BLOCKS:
        raise ValueError(f"unsupported depth {depth}")
    sd = state_dict
    params: Dict[str, Any] = {"stem": _fold_bn(sd, "conv1", "bn1")}
    for stage, blocks in enumerate(STAGE_BLOCKS[depth]):
        for block in range(blocks):
            prefix = f"layer{stage + 1}.{block}"
            p = {
                "conv1": _fold_bn(sd, f"{prefix}.conv1", f"{prefix}.bn1"),
                "conv2": _fold_bn(sd, f"{prefix}.conv2", f"{prefix}.bn2"),
                "conv3": _fold_bn(sd, f"{prefix}.conv3", f"{prefix}.bn3"),
            }
            if f"{prefix}.downsample.0.weight" in sd:
                p["proj"] = _fold_bn(sd, f"{prefix}.downsample.0",
                                     f"{prefix}.downsample.1")
            params[f"s{stage}b{block}"] = p
    params["fc"] = {
        "kernel": np.asarray(sd["fc.weight"], np.float32).T,
        "bias": np.asarray(sd["fc.bias"], np.float32),
    }
    return params


def make_resnet_from_torch(state_dict_or_path, depth: int = 50,
                           **make_kwargs):
    """Build a servable Model from a torch checkpoint (path or state_dict)."""
    if isinstance(state_dict_or_path, (str, bytes)):
        import torch
        state_dict = torch.load(state_dict_or_path, map_location="cpu",
                                weights_only=True)
    else:
        state_dict = state_dict_or_path
    if hasattr(next(iter(state_dict.values())), "detach"):
        state_dict = {k: v.detach().cpu().numpy()
                      for k, v in state_dict.items()}
    from tpulab.models.resnet import make_resnet
    model = make_resnet(depth=depth, **make_kwargs)
    model.params = resnet_params_from_torch(state_dict, depth)
    return model
