"""ResNet v1.5 (50/101/152) in Flax — the serving flagship
(reference models/ResNet-50/152 prototxt + examples/ONNX/resnet50 build
pipeline; the benchmark model of BASELINE.md).

TPU-first choices:
- NHWC layout (XLA:TPU's native conv layout — channels on the 128-lane axis)
- bf16 compute / f32 params ("mixed" policy): convs hit the MXU at full rate
- inference-mode BatchNorm folded to scale+bias at build time (no batch_stats
  plumbing in the serving path, same as TRT's BN folding)
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

STAGE_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


def _conv_init(key, shape, dtype=jnp.float32):
    fan_in = np.prod(shape[:-1])
    std = np.sqrt(2.0 / fan_in)
    return jax.random.normal(key, shape, dtype) * std


def _init_conv_bn(key, kh, kw, cin, cout):
    """One conv + folded-BN unit: returns params dict."""
    kconv, _ = jax.random.split(key)
    return {
        "kernel": _conv_init(kconv, (kh, kw, cin, cout)),
        # folded BN: y = scale * conv(x) + bias (identity at init)
        "scale": jnp.ones((cout,), jnp.float32),
        "bias": jnp.zeros((cout,), jnp.float32),
    }


def _conv_bn(params, x, stride=1, relu=True, compute_dtype=jnp.bfloat16,
             name="", observe=None):
    if observe is not None:
        # calibration mode: record this unit's input activation absmax
        observe[name] = jnp.abs(x.astype(jnp.float32)).max()
    kernel = params["kernel"]
    if kernel.dtype == jnp.int8 and "act_scale" in params:
        # W8A8: quantize the activation with the calibrated scale, run the
        # conv in int8 with int32 accumulation (MXU-native), dequantize in
        # the epilogue with act_scale * per-channel kernel_scale
        act_scale = params["act_scale"].astype(jnp.float32)
        xq = jnp.clip(jnp.round(x.astype(jnp.float32) / act_scale),
                      -127, 127).astype(jnp.int8)
        y = jax.lax.conv_general_dilated(
            xq, kernel, window_strides=(stride, stride), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        y = (y.astype(jnp.float32)
             * (act_scale * params["kernel_scale"].astype(jnp.float32))
             ).astype(compute_dtype)
    else:
        if kernel.dtype == jnp.int8:
            # weight-only INT8: dequantize per output channel in-compute
            # (XLA fuses the scale into the conv epilogue); 4x less HBM
            kernel = kernel.astype(compute_dtype) * \
                params["kernel_scale"].astype(compute_dtype)
        else:
            kernel = kernel.astype(compute_dtype)
        y = jax.lax.conv_general_dilated(
            x.astype(compute_dtype), kernel,
            window_strides=(stride, stride),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    y = y * params["scale"].astype(compute_dtype) + params["bias"].astype(compute_dtype)
    if relu:
        y = jax.nn.relu(y)
    return y


def _init_bottleneck(key, cin, cmid, cout, stride):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    p = {
        "conv1": _init_conv_bn(k1, 1, 1, cin, cmid),
        "conv2": _init_conv_bn(k2, 3, 3, cmid, cmid),
        "conv3": _init_conv_bn(k3, 1, 1, cmid, cout),
    }
    if stride != 1 or cin != cout:
        p["proj"] = _init_conv_bn(k4, 1, 1, cin, cout)
    return p


def _bottleneck(params, x, stride, compute_dtype, name="", observe=None):
    """v1.5 bottleneck: stride on the 3x3 conv."""
    residual = x
    y = _conv_bn(params["conv1"], x, 1, True, compute_dtype,
                 f"{name}/conv1", observe)
    y = _conv_bn(params["conv2"], y, stride, True, compute_dtype,
                 f"{name}/conv2", observe)
    y = _conv_bn(params["conv3"], y, 1, False, compute_dtype,
                 f"{name}/conv3", observe)
    if "proj" in params:
        residual = _conv_bn(params["proj"], x, stride, False, compute_dtype,
                            f"{name}/proj", observe)
    return jax.nn.relu(y + residual.astype(y.dtype))


def init_resnet_params(depth: int = 50, num_classes: int = 1000,
                       seed: int = 0) -> Dict[str, Any]:
    """Random (He-init) weights; BN folded to identity scale/bias."""
    if depth not in (50, 101, 152):
        raise ValueError(f"unsupported ResNet depth {depth}")
    rng = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(rng, 64))
    params: Dict[str, Any] = {"stem": _init_conv_bn(next(keys), 7, 7, 3, 64)}
    cin = 64
    for stage, blocks in enumerate(STAGE_SIZES[depth]):
        cmid = 64 * (2 ** stage)
        cout = cmid * 4
        for block in range(blocks):
            stride = 2 if (block == 0 and stage > 0) else 1
            params[f"s{stage}b{block}"] = _init_bottleneck(
                next(keys), cin, cmid, cout, stride)
            cin = cout
    kfc = next(keys)
    params["fc"] = {
        "kernel": jax.random.normal(kfc, (cin, num_classes)) * 0.01,
        "bias": jnp.zeros((num_classes,)),
    }
    return params


IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def resnet_apply(params: Dict[str, Any], inputs: Dict[str, jnp.ndarray],
                 depth: int = 50, compute_dtype=jnp.bfloat16,
                 observe: Dict[str, Any] = None) -> Dict[str, jnp.ndarray]:
    """Forward pass: NHWC image -> logits (binding names: input / logits).

    uint8 inputs are normalized on device ((x/255 - mean)/std in bf16) — the
    parity path for the reference's INT8-input engines (examples/ONNX int8.py
    calibrated pipeline): the wire/staging payload is 1 byte/pixel and all
    arithmetic stays on the MXU-friendly dtype.
    """
    x = inputs["input"]
    if x.dtype == jnp.uint8:
        mean = jnp.asarray(IMAGENET_MEAN, compute_dtype) * 255.0
        std = jnp.asarray(IMAGENET_STD, compute_dtype) * 255.0
        x = (x.astype(compute_dtype) - mean) / std
    y = _conv_bn(params["stem"], x, 2, True, compute_dtype, "stem", observe)
    y = jax.lax.reduce_window(
        y, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])
    for stage, blocks in enumerate(STAGE_SIZES[depth]):
        for block in range(blocks):
            stride = 2 if (block == 0 and stage > 0) else 1
            y = _bottleneck(params[f"s{stage}b{block}"], y, stride,
                            compute_dtype, f"s{stage}b{block}", observe)
    y = jnp.mean(y, axis=(1, 2))  # global average pool
    logits = (y.astype(jnp.float32) @ params["fc"]["kernel"]
              + params["fc"]["bias"])
    return {"logits": logits}


def resnet_collect_amax(params, x, depth: int = 50,
                        compute_dtype=jnp.float32):
    """Calibration forward: per-conv-unit input-activation absmax
    (the per-layer ranges the reference's INT8 calibrator records)."""
    observe: Dict[str, jnp.ndarray] = {}
    resnet_apply(params, {"input": x}, depth, compute_dtype, observe=observe)
    return observe


def make_resnet(depth: int = 50, num_classes: int = 1000,
                image_size: int = 224, max_batch_size: int = 8,
                compute_dtype=jnp.bfloat16, seed: int = 0,
                input_dtype=np.float32, batch_buckets=None, params=None):
    """Build a servable ResNet Model.

    ``input_dtype=np.uint8`` selects the INT8-parity serving path: raw pixel
    bytes in, on-device normalization (4x less ingress bandwidth).
    ``params`` reuses an existing parameter pytree (several Model views of
    one weight set, e.g. different bucket plans, without re-init).
    """
    from tpulab.engine.model import IOSpec, Model

    if params is None:
        params = init_resnet_params(depth, num_classes, seed)
    apply_fn = partial(resnet_apply, depth=depth, compute_dtype=compute_dtype)
    return Model(
        name=f"resnet{depth}",
        apply_fn=apply_fn,
        params=params,
        inputs=[IOSpec("input", (image_size, image_size, 3), input_dtype)],
        outputs=[IOSpec("logits", (num_classes,), np.float32)],
        max_batch_size=max_batch_size,
        batch_buckets=batch_buckets,
    )
