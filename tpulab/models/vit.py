"""Vision Transformer family (ViT-S/B/L at /16 or /32 patching).

The reference's model zoo is ONNX-engine classifiers with ResNet-50 as
the flagship (reference examples/00_TensorRT, models/setup.py); this adds
the transformer-class image model the TPU way rather than porting an ONNX
graph:

- **Patch embedding is one reshape + matmul**: (B, H, W, C) reshapes to
  (B, N, p*p*C) — a free layout change — and a single (p*p*C, d) matmul
  embeds every patch on the MXU.  No conv, no im2col materialization.
- **Encoder blocks reuse the transformer trunk primitives**
  (:mod:`tpulab.models.transformer`: ``_rmsnorm``, ``dense_attention``,
  ``qmat``) — pre-norm blocks with non-causal attention.  RMSNorm instead
  of classic LayerNorm is a deliberate in-house choice: one fused
  rsqrt-scale, no mean subtraction or bias, same layer dict layout as the
  text transformer so weight-only INT8 (``quantize_transformer_params``)
  applies unchanged.
- **uint8 ingress** shares ResNet's INT8-parity serving path: raw pixel
  bytes over wire/staging (4x less ingress), normalization fused on
  device.

Servable via ``build_model("vit_s16" | "vit_b16" | ...)`` with batch
buckets like every zoo model.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from tpulab.models.resnet import IMAGENET_MEAN, IMAGENET_STD
from tpulab.models.transformer import _rmsnorm, dense_attention, qmat

_GEOMETRIES = {  # name -> (d_model, n_heads, n_layers, d_ff)
    "s": (384, 6, 12, 1536),
    "b": (768, 12, 12, 3072),
    "l": (1024, 16, 24, 4096),
}


def init_vit_params(variant: str = "s", image_size: int = 224,
                    patch_size: int = 16, num_classes: int = 1000,
                    seed: int = 0) -> Dict[str, Any]:
    d_model, n_heads, n_layers, d_ff = _GEOMETRIES[variant]
    if image_size % patch_size:
        raise ValueError(f"image {image_size} not divisible by patch "
                         f"{patch_size}")
    n_patches = (image_size // patch_size) ** 2
    rng = jax.random.PRNGKey(seed)
    keys = iter(jax.random.split(rng, n_layers + 5))
    s = 0.02
    params: Dict[str, Any] = {
        "patch_embed": jax.random.normal(
            next(keys), (patch_size * patch_size * 3, d_model)) * s,
        "cls": jax.random.normal(next(keys), (d_model,)) * s,
        "pos_embed": jax.random.normal(
            next(keys), (n_patches + 1, d_model)) * s,
        "final_norm": {"scale": jnp.ones((d_model,))},
        "head": {
            "kernel": jax.random.normal(next(keys),
                                        (d_model, num_classes)) * s,
            "bias": jnp.zeros((num_classes,)),
        },
    }
    for i in range(n_layers):
        lkeys = iter(jax.random.split(next(keys), 4))
        params[f"layer{i}"] = {
            "ln1": {"scale": jnp.ones((d_model,))},
            "ln2": {"scale": jnp.ones((d_model,))},
            "wqkv": jax.random.normal(next(lkeys),
                                      (d_model, 3 * d_model)) * s,
            "wo": jax.random.normal(next(lkeys), (d_model, d_model)) * s,
            "w1": jax.random.normal(next(lkeys), (d_model, d_ff)) * s,
            "w2": jax.random.normal(next(lkeys), (d_ff, d_model)) * s,
        }
    return params


def _norm(x, np_, compute_dtype):
    """RMSNorm (in-house layout: {scale}) or classic LayerNorm when the
    checkpoint carries a bias ({scale, bias} — the HF/torchvision
    family): one predicate keys the faithful-import path."""
    if "bias" in np_:
        eps = np_.get("eps", 1e-6)  # HF stores its config eps (1e-12)
        # statistics in f32 EXPLICITLY: under bf16 compute, mean/var of a
        # bf16 x are themselves bf16 (a weakly-typed python eps does not
        # promote the reduction inputs), and bf16 LN stats drift imported
        # checkpoints' numerics away from HF's f32 LayerNorm.  The OUTPUT
        # drops back to compute_dtype so the promotion never leaks into
        # the matmuls.
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, -1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), -1, keepdims=True)
        xn = ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(compute_dtype)
        return (xn * np_["scale"].astype(compute_dtype)
                + np_["bias"].astype(compute_dtype)).astype(compute_dtype)
    return _rmsnorm(x, np_["scale"].astype(compute_dtype))


def _badd(h, lp, key, compute_dtype):
    b = lp.get(key)
    return h if b is None else h + b.astype(compute_dtype)


def vit_apply(params: Dict[str, Any], inputs: Dict[str, jnp.ndarray],
              n_heads: int, n_layers: int, patch_size: int = 16,
              compute_dtype=jnp.bfloat16) -> Dict[str, jnp.ndarray]:
    """Forward: NHWC image -> logits (binding names: input / logits).
    uint8 inputs are normalized on device, like the ResNet serving path.

    Two weight dialects share this function: the in-house init (RMSNorm,
    bias-free, tanh-gelu — the TPU-first default) and faithfully
    imported classic checkpoints (HF ViT: LayerNorm with bias, biased
    projections, exact erf-gelu).  Bias presence in the norm dicts picks
    the dialect — the checkpoint defines the function, no flags to
    mismatch."""
    x = inputs["input"]
    if x.dtype == jnp.uint8:
        # imported checkpoints carry their processor's normalization
        # (HF ViT uses mean=std=0.5, NOT the imagenet stats)
        mean = params.get("norm_mean")
        std = params.get("norm_std")
        mean = (jnp.asarray(IMAGENET_MEAN, compute_dtype) if mean is None
                else mean.astype(compute_dtype)) * 255.0
        std = (jnp.asarray(IMAGENET_STD, compute_dtype) if std is None
               else std.astype(compute_dtype)) * 255.0
        x = (x.astype(compute_dtype) - mean) / std
    else:
        x = x.astype(compute_dtype)
    classic = "bias" in params["final_norm"]
    b, hh, ww, c = x.shape
    p = patch_size
    # patchify = pure layout: (B, Hp, p, Wp, p, C) -> (B, N, p*p*C)
    x = x.reshape(b, hh // p, p, ww // p, p, c).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(b, (hh // p) * (ww // p), p * p * c)
    x = x @ qmat(params["patch_embed"], compute_dtype)
    if "patch_bias" in params:
        x = x + params["patch_bias"].astype(compute_dtype)
    cls = jnp.broadcast_to(params["cls"].astype(compute_dtype),
                           (b, 1, x.shape[-1]))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(compute_dtype)[None]
    t, d_model = x.shape[1], x.shape[2]
    head_dim = d_model // n_heads
    for i in range(n_layers):
        lp = params[f"layer{i}"]
        h = _norm(x, lp["ln1"], compute_dtype)
        qkv = _badd(h @ qmat(lp["wqkv"], compute_dtype), lp, "bqkv",
                    compute_dtype)
        q, k, v = (qkv[..., j * d_model:(j + 1) * d_model]
                   .reshape(b, t, n_heads, head_dim) for j in range(3))
        attn = dense_attention(q, k, v, causal=False).reshape(b, t, d_model)
        x = x + _badd(attn @ qmat(lp["wo"], compute_dtype), lp, "bo",
                      compute_dtype)
        h = _norm(x, lp["ln2"], compute_dtype)
        h = _badd(h @ qmat(lp["w1"], compute_dtype), lp, "b1", compute_dtype)
        h = jax.nn.gelu(h, approximate=not classic)
        x = x + _badd(h @ qmat(lp["w2"], compute_dtype), lp, "b2",
                      compute_dtype).astype(x.dtype)
    x = _norm(x, params["final_norm"], compute_dtype)
    logits = (x[:, 0].astype(jnp.float32) @ params["head"]["kernel"]
              + params["head"]["bias"])
    return {"logits": logits}


def make_vit(variant: str = "s", image_size: int = 224,
             patch_size: int = 16, num_classes: int = 1000,
             max_batch_size: int = 8, compute_dtype=jnp.bfloat16,
             seed: int = 0, input_dtype=np.float32, batch_buckets=None,
             params=None):
    """Build a servable ViT Model (same surface as :func:`make_resnet`)."""
    from tpulab.engine.model import IOSpec, Model

    _, n_heads, n_layers, _ = _GEOMETRIES[variant]
    if params is None:
        params = init_vit_params(variant, image_size, patch_size,
                                 num_classes, seed)
    apply_fn = partial(vit_apply, n_heads=n_heads, n_layers=n_layers,
                       patch_size=patch_size, compute_dtype=compute_dtype)
    return Model(
        name=f"vit_{variant}{patch_size}",
        apply_fn=apply_fn,
        params=params,
        inputs=[IOSpec("input", (image_size, image_size, 3), input_dtype)],
        outputs=[IOSpec("logits", (num_classes,), np.float32)],
        max_batch_size=max_batch_size,
        batch_buckets=batch_buckets,
    )
