"""Serving frontend: admission control & QoS (docs/SERVING.md).

The explicit layer between the gRPC services and the engines: decide at
the RPC boundary whether a request runs, waits in a per-tenant fair
queue, or fails fast with ``RESOURCE_EXHAUSTED`` + ``retry_after_ms`` —
before it consumes a lane, KV pages, or a session lease.
"""

from tpulab.serving.admission import (DEFAULT_TENANT,  # noqa: F401
                                      REQUEST_CLASS_BATCH,
                                      REQUEST_CLASS_ONLINE, REQUEST_CLASSES,
                                      TENANT_METADATA_KEY, AdmissionConfig,
                                      AdmissionController, AdmissionRejected,
                                      AdmissionTicket, TokenBucket,
                                      tenant_of_request)
from tpulab.serving.fair_queue import DeficitRoundRobinQueue  # noqa: F401
