"""Admission control & QoS for the serving frontend.

trtlab's only backpressure is implicit — callers block on resource-pool
leases (SURVEY §2.5) — which collapses under heavy traffic: every request
is accepted, queues grow without bound, and decode steps are burned on
requests whose deadlines expired while they waited.  This module is the
explicit admission layer the ROADMAP north star calls for: decide AT THE
RPC BOUNDARY whether a request should run, wait, or fail fast — before it
consumes a lane, KV pages, or a session lease (the cost/performance/
resilience balancing of the adaptive-orchestration line in PAPERS.md).

:class:`AdmissionController` composes, in decision order:

1. **Token-bucket rate limits** — global and per-tenant (identity from
   the request's ``tenant_id`` field or the ``tpulab-tenant`` gRPC
   metadata key).  Rate rejections fail fast with a ``retry_after_ms``
   hint; they never occupy queue space.
2. **Bounded inflight + queue-depth caps, cost-aware** — estimated cost
   is ``prompt tokens + steps``; a request is only dispatched when the
   attached load source (a :class:`~tpulab.engine.paged.ContinuousBatcher`)
   has the free KV pages and lane headroom to run it.
3. **Deadline-aware early rejection** — predicted queue wait (EWMA of
   observed service time × queue position) exceeding the remaining
   ``deadline_ms`` rejects immediately instead of burning decode steps on
   a request that cannot finish in time.
4. **Priority-ordered load shedding** — when the bounded queue overflows,
   the globally lowest-priority queued request is shed first; an arrival
   that does not outrank the lowest queued request is itself rejected.
5. **Deficit-round-robin fair queuing** (serving/fair_queue.py) — queued
   admissions dispatch in cost-weighted round robin across tenants, so
   one greedy tenant cannot starve the rest.

Every rejection carries a machine-readable ``reason`` and a
``retry_after_ms`` hint; the RPC layer maps it to the
``RESOURCE_EXHAUSTED`` status and clients honor the hint with jittered
backoff (``rpc/client.py::jittered_backoff_s``, replica sets route away).

The ``serving.admission`` chaos trip point (tpulab.chaos) forces the
overload path on demand: an ``error``/``drop`` rule converts to a
rejection (reason ``chaos``), ``delay`` models a slow admission decision.

Disarmed cost: services built without a controller pay one ``is None``
branch per request — the default-off contract.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from tpulab import chaos
from tpulab.core.deadline import Deadline
from tpulab.serving.fair_queue import DeficitRoundRobinQueue

#: gRPC metadata key carrying the tenant identity (the request's
#: ``tenant_id`` field is the primary channel; metadata rides along for
#: middleboxes that never parse the payload — mirrors the trace-id pair)
TENANT_METADATA_KEY = "tpulab-tenant"

#: tenant label for requests that carry no identity
DEFAULT_TENANT = "default"

#: request classes (the offline batch lane, docs/SERVING.md "Offline
#: batch lane").  ONLINE is interactive traffic — today's behavior,
#: unchanged.  BATCH is preemptible bulk work (scoring, evals,
#: distillation traces) that admits STRICTLY below any online priority:
#: it dispatches only when no online request waits, rides its own
#: fair queue so a batch flood never moves an online tenant's DRR
#: deficit, and is excluded from the queue-wait EWMA the fleet
#: autoscaler scales on (preemptible work must never buy replicas).
REQUEST_CLASS_ONLINE = "online"
REQUEST_CLASS_BATCH = "batch"
REQUEST_CLASSES = (REQUEST_CLASS_ONLINE, REQUEST_CLASS_BATCH)

#: rejection reasons (the ``reason`` label on AdmissionMetrics.rejected)
REJECT_REASONS = ("global_rate", "tenant_rate", "queue_full", "shed",
                  "deadline", "queue_timeout", "chaos")


def tenant_of_request(request, grpc_context=None,
                      default: str = DEFAULT_TENANT) -> str:
    """Server-side tenant recovery: the request's ``tenant_id`` field
    first, else the ``tpulab-tenant`` invocation metadata, else the
    default tenant (mirrors TraceContext.of_request)."""
    t = getattr(request, "tenant_id", "")
    if t:
        return str(t)
    if grpc_context is not None and hasattr(grpc_context,
                                            "invocation_metadata"):
        try:
            for k, v in grpc_context.invocation_metadata() or ():
                if k == TENANT_METADATA_KEY and v:
                    return str(v)
        except Exception:  # pragma: no cover - exotic grpc shims
            pass
    return default


class AdmissionRejected(RuntimeError):
    """The admission controller refused the request.  ``reason`` is one
    of :data:`REJECT_REASONS`; ``retry_after_ms`` is the server's backoff
    hint (0 = no hint, e.g. the request's own deadline was the limit)."""

    def __init__(self, reason: str, message: str, retry_after_ms: int = 0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_ms = int(retry_after_ms)


@dataclass
class AdmissionConfig:
    """Admission knobs (docs/SERVING.md).  ``max_inflight`` bounds
    concurrently admitted requests; ``max_queue_depth`` bounds waiting
    ones — together they are the whole memory footprint of overload.
    Rates are requests/second (0 disables a bucket); bursts default to
    one second of rate (min 1).  ``drr_quantum`` is the fair-queue
    quantum in cost units (estimated tokens).  ``expected_service_s``
    seeds the service-time EWMA the wait predictor uses before any
    completion has been observed."""

    max_inflight: int = 8
    max_queue_depth: int = 32
    global_rate: float = 0.0
    global_burst: float = 0.0
    tenant_rate: float = 0.0
    tenant_burst: float = 0.0
    drr_quantum: int = 512
    admit_wait_s: float = 30.0
    min_retry_after_ms: int = 25
    max_retry_after_ms: int = 5000
    expected_service_s: float = 0.0
    #: distinct per-tenant buckets kept before the stalest is evicted
    #: (an unauthenticated tenant header must not be a memory leak)
    tenant_bucket_cap: int = 4096
    #: per-model admission-cost multipliers (multi-model serving,
    #: docs/SERVING.md "Multi-model serving"): a heavyweight model's
    #: requests charge more of the shared capacity than a tiny one's.
    #: Models not listed cost 1.0x.
    model_costs: Optional[Dict[str, float]] = None
    #: per-model base-priority boosts added to each request's own
    #: priority (shedding order + queue ranking): a latency-critical
    #: model's traffic outranks a batch model's at overflow
    model_priorities: Optional[Dict[str, int]] = None
    #: bound on WAITING batch-class admissions (the batch lane's own
    #: fair queue, never shared with online waiters); None = the online
    #: ``max_queue_depth`` value
    max_batch_queue_depth: Optional[int] = None
    #: arbiter headroom floor for batch dispatch: with an HBM arbiter
    #: armed, batch work only dispatches while ``free_hbm_bytes`` stays
    #: at or above this — spare capacity means ACTUALLY spare, not
    #: bytes a pressure round is about to hand to an online tenant
    batch_min_free_hbm_bytes: int = 0


class TokenBucket:
    """Lazy-refill token bucket.  NOT internally locked — the controller's
    lock guards it (``clock`` injectable for deterministic tests)."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_clock")

    def __init__(self, rate: float, burst: float = 0.0, clock=time.monotonic):
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill(self._clock())
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (0 if already)."""
        self._refill(self._clock())
        missing = n - self._tokens
        return 0.0 if missing <= 0 else missing / self.rate


class AdmissionTicket:
    """One admitted request's capacity hold; ``release()`` (or context
    exit) returns it and dispatches the next queued admission."""

    __slots__ = ("tenant", "cost", "model", "queue_wait_s", "drr_deficit",
                 "request_class", "_ctrl", "_t_admit", "_released")

    def __init__(self, ctrl: "AdmissionController", tenant: str, cost: int,
                 queue_wait_s: float, model: str = "",
                 drr_deficit: float = 0.0,
                 request_class: str = REQUEST_CLASS_ONLINE):
        self.tenant = tenant
        self.cost = cost
        self.model = model
        #: "online" or "batch" (REQUEST_CLASSES) — batch tickets never
        #: feed the queue-wait EWMA the autoscaler scales on
        self.request_class = request_class
        self.queue_wait_s = queue_wait_s
        #: the tenant's deficit-round-robin credit at dispatch (0.0 on
        #: the no-queue fast path) — a wide event (tpulab.obs) records it
        #: so "why did this tenant's request wait" is answerable per
        #: request, not just per aggregate
        self.drr_deficit = drr_deficit
        self._ctrl = ctrl
        self._t_admit = time.perf_counter()
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ctrl._on_release(self)

    def __enter__(self) -> "AdmissionTicket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _Waiter:
    """A queued admission request (entry in the DRR queue)."""

    __slots__ = ("tenant", "cost", "model", "priority", "deadline", "seq",
                 "event", "ticket", "reject", "t_enqueue", "request_class")

    def __init__(self, tenant: str, cost: int, priority: int,
                 deadline: Optional[Deadline], seq: int, model: str = "",
                 request_class: str = REQUEST_CLASS_ONLINE):
        self.tenant = tenant
        self.cost = cost
        self.model = model
        self.request_class = request_class
        self.priority = priority
        self.deadline = deadline
        self.seq = seq
        self.event = threading.Event()
        self.ticket: Optional[AdmissionTicket] = None
        self.reject: Optional[AdmissionRejected] = None
        self.t_enqueue = time.perf_counter()


class AdmissionController:
    """The serving frontend's admission decision (module docstring).

    ``load`` is an optional load source for cost-aware admission — any
    object exposing ContinuousBatcher's surface (``lanes``,
    ``active_lanes``, ``queued_requests``, ``page_size``,
    ``pool.free_pages``); absent attributes disable that signal.
    ``metrics`` is an optional
    :class:`tpulab.utils.metrics.AdmissionMetrics`; ``trace`` an optional
    ChromeTraceRecorder (one ``admission`` span per decision).
    """

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 load=None, metrics=None, trace=None, modelstore=None,
                 hbm=None):
        self.config = config or AdmissionConfig()
        self._load = load
        self._metrics = metrics
        self.trace = trace
        #: optional tpulab.modelstore.WeightMultiplexer — the per-model
        #: capacity gate: a request for a model that cannot be made
        #: HBM-resident without evicting a leased/pinned/decode-active
        #: model QUEUES (never thrashes the hot working set); adopted by
        #: build_infer_service when a modelstore is served
        self.modelstore = modelstore
        #: optional tpulab.hbm.HBMArbiter — the unified device-memory
        #: economy.  Armed, _capacity_ok_locked consults the arbiter's
        #: ONE headroom number (free + reclaimable-under-pressure bytes)
        #: instead of summing the KV tier's and the modelstore's
        #: optimistic per-tenant estimates; adopted by
        #: build_infer_service when an arbiter is served
        self.hbm = hbm
        cfg = self.config
        self._lock = threading.Lock()
        self._queue = DeficitRoundRobinQueue(quantum=cfg.drr_quantum)
        #: batch-class waiters ride their OWN fair queue (docs/SERVING.md
        #: "Offline batch lane"): a batch flood must not occupy online
        #: queue slots or move any online tenant's DRR deficit, and batch
        #: dispatch happens only when no online waiter remains
        self._batch_queue = DeficitRoundRobinQueue(quantum=cfg.drr_quantum)
        self._inflight = 0
        self._seq = 0
        self._global_bucket = (TokenBucket(cfg.global_rate, cfg.global_burst)
                               if cfg.global_rate > 0 else None)
        self._tenant_buckets: Dict[str, TokenBucket] = {}
        self._service_ewma = (cfg.expected_service_s
                              if cfg.expected_service_s > 0 else None)
        #: EWMA of the queue wait admitted requests ACTUALLY paid
        #: (ticket.queue_wait_s at admit; 0.0 rides the no-queue fast
        #: path, pulling the average down when capacity is plentiful) —
        #: the fleet autoscaler's scale-up/down signal
        #: (tpulab.fleet.FleetAutoscaler wait_signal, docs/SERVING.md
        #: "Fleet routing & autoscaling")
        self._queue_wait_ewma: Optional[float] = None
        # -- observability (test-assertable without prometheus) -------------
        self.admitted_total = 0
        self.batch_admitted_total = 0
        self.rejected_total = 0
        self.shed_total = 0
        self.rejected_by_reason: Dict[str, int] = {}
        self.peak_queue_depth = 0
        #: live admitted requests per model name (the multi-model load
        #: view; "" aggregates requests that carried no model)
        self.model_inflight: Dict[str, int] = {}

    # -- load signals --------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        """ONLINE waiters only: queued batch work is preemptible filler
        that yields its capacity within one tick, so it must not make
        this replica look loaded to routers (or to the autoscaler)."""
        with self._lock:
            return len(self._queue)

    @property
    def batch_queue_depth(self) -> int:
        """Waiting batch-class admissions (the offline lane's backlog)."""
        with self._lock:
            return len(self._batch_queue)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def queue_depths(self) -> Dict[str, int]:
        """Queued admissions per tenant (the debugz live view); batch
        tenants are namespaced ``batch:<tenant>`` — their waiters live
        in the offline lane's own fair queue."""
        with self._lock:
            depths = self._queue.depths()
            for t, n in self._batch_queue.depths().items():
                depths[f"batch:{t}"] = n
            return depths

    @property
    def queue_wait_ewma_s(self) -> float:
        """EWMA of the queue wait admitted ONLINE requests actually paid
        (seconds; 0.0 before any admission) — the load signal the fleet
        autoscaler scales on: waiting requests mean the fleet is short a
        replica long before anything is rejected.  Batch-class
        admissions are excluded by construction: the offline lane waits
        for spare capacity on purpose, and preemptible filler must
        never look like demand worth buying a replica for."""
        with self._lock:
            return self._queue_wait_ewma or 0.0

    def _capacity_ok_locked(self, cost: int, model: str = "") -> bool:
        """Cost-aware dispatch gate: the load source must have the free KV
        pages to hold ``cost`` tokens and lane headroom to schedule the
        request soon (at most one lane-set's worth queued inside the
        engine — the admission queue is where waiting happens).

        Mesh-invariant by construction (docs/SERVING.md): under sharded
        serving page *tables* are replicated and page *payloads* split
        over the model axis, so counting LOGICAL free pages is already
        the per-shard headroom — one free page is page_nbytes/M bytes
        free on every shard at once."""
        arb = self.hbm
        ms = self.modelstore
        if arb is None and ms is not None and model:
            # pre-arbiter multi-model gate (one of the two per-tenant
            # estimates the unified headroom replaces below)
            try:
                if not ms.can_admit(model):
                    # multi-model serving: this model's weights cannot be
                    # made resident without evicting a leased/pinned/
                    # decode-active model — a burst on model A queues here
                    # instead of thrashing model B's working set mid-decode
                    return False
            except Exception:  # a torn-down store must not wedge admission
                pass
        eng = self._load
        if eng is None:
            return True
        try:
            # speculative decoding holds a draft page table next to the
            # target's and burns draft+verify compute on rejected
            # proposals — the engine reports how much bigger a request
            # really is (ContinuousBatcher.admission_cost_factor);
            # drafted-but-rejected tokens are not free
            cost = int(cost * float(getattr(eng, "admission_cost_factor",
                                            1.0) or 1.0))
            pool = getattr(eng, "pool", None)
            if pool is not None:
                page_size = int(getattr(eng, "page_size", 0)
                                or getattr(pool, "page_size", 1))
                free = int(pool.free_pages)
                if arb is not None:
                    # unified HBM economy (tpulab.hbm): ONE honest
                    # headroom — free pool pages plus what the ledger has
                    # free or pressure could reclaim from the OTHER
                    # tenants (evictable cold models, measured scratch
                    # never double-counted) — instead of summing the KV
                    # tier's and the modelstore's optimistic estimates
                    pn = max(1, int(getattr(pool, "page_nbytes", 0) or 1))
                    extra = (max(0, int(arb.free_hbm_bytes))
                             + int(arb.reclaimable_bytes(exclude="kv")))
                    if ((free + extra // pn) * max(1, page_size)
                            < cost):
                        return False
                elif free * max(1, page_size) < cost:
                    # tiered KV (tpulab.kvcache): pages the engine can
                    # DEMOTE to the host tier instead of dropping count as
                    # headroom — admission sees the effective (HBM + host)
                    # capacity, not just free HBM pages
                    off = getattr(eng, "kv_offload", None)
                    if off is not None:
                        free += int(off.demotable_pages(
                            getattr(eng, "prefix_cache", None)))
                    if free * max(1, page_size) < cost:
                        return False
            lanes = int(getattr(eng, "lanes", 0) or 0)
            if lanes and (int(getattr(eng, "active_lanes", 0)) >= lanes
                          and int(getattr(eng, "queued_requests", 0))
                          >= lanes):
                return False
        except Exception:  # a torn-down pool must not wedge admission
            return True
        return True

    def headroom_ok(self, cost: int, model: str = "") -> bool:
        """Public view of the cost-aware dispatch gate — the ONE unified
        headroom admission itself consults (free pool pages, demotable
        KV, arbiter free + reclaimable bytes).  The batch lane's
        spare-capacity probe (tpulab.batch.BatchScheduler) reads it here
        instead of re-deriving its own optimistic estimate."""
        with self._lock:
            return self._capacity_ok_locked(max(1, int(cost)), model)

    def _batch_spare_locked(self, cost: int, model: str = "") -> bool:
        """Spare-capacity gate for batch-class dispatch (docs/SERVING.md
        "Offline batch lane"): no online waiter may remain (batch sits
        strictly below any online priority), the engine must have an
        IDLE lane (batch never queues inside the engine where it could
        delay an online admit), the unified headroom must cover the
        cost, and with an arbiter armed ``free_hbm_bytes`` must sit at
        or above the configured floor — spare means actually spare, not
        bytes a pressure round is about to hand to an online tenant."""
        if len(self._queue):
            return False
        if not self._capacity_ok_locked(cost, model):
            return False
        eng = self._load
        if eng is not None:
            try:
                lanes = int(getattr(eng, "lanes", 0) or 0)
                if lanes and (int(getattr(eng, "active_lanes", 0)) >= lanes
                              or int(getattr(eng, "queued_requests", 0))
                              > 0):
                    return False
            except Exception:  # torn-down engine: the capacity gate ruled
                pass
        arb = self.hbm
        floor = int(self.config.batch_min_free_hbm_bytes)
        if arb is not None and floor > 0:
            try:
                if int(arb.free_hbm_bytes) < floor:
                    return False
            except Exception:  # torn-down arbiter must not wedge batch
                pass
        return True

    # -- estimators ----------------------------------------------------------
    def _predicted_wait_locked(self, position: int) -> float:
        """Expected queue wait at ``position`` (0 = head): EWMA service
        time × slots ahead / parallelism.  0 before any observation — a
        guess must not reject real traffic."""
        if self._service_ewma is None:
            return 0.0
        par = max(1, self.config.max_inflight)
        return self._service_ewma * (position + 1) / par

    def _retry_hint_ms_locked(self) -> int:
        cfg = self.config
        est = self._predicted_wait_locked(len(self._queue))
        ms = int(est * 1e3) if est > 0 else cfg.min_retry_after_ms
        return max(cfg.min_retry_after_ms, min(cfg.max_retry_after_ms, ms))

    # -- the decision --------------------------------------------------------
    def admit(self, tenant: str = "", cost: int = 1, priority: int = 0,
              deadline: Optional[Deadline] = None,
              trace_id: Optional[str] = None,
              model: str = "",
              request_class: str = REQUEST_CLASS_ONLINE
              ) -> AdmissionTicket:
        """Admit (possibly after a bounded fair-queue wait) or raise
        :class:`AdmissionRejected`.  ``cost`` is estimated tokens
        (prompt + steps) for generation, batch size for dense inference.
        ``model`` arms the per-model dimension (multi-model serving):
        the configured per-model cost multiplier and priority boost
        apply, the modelstore residency gate is consulted, and the
        request counts in :attr:`model_inflight`.  ``request_class``
        (:data:`REQUEST_CLASSES`; ""/"online" = interactive) marks the
        offline batch lane: batch admissions dispatch strictly below any
        online work, from spare capacity only, ride their own fair
        queue (a batch flood never moves an online tenant's DRR
        deficit) and never feed the queue-wait EWMA the fleet
        autoscaler scales on.  The returned ticket MUST be released when
        the request finishes (context manager)."""
        t0 = time.perf_counter()
        tenant = tenant or DEFAULT_TENANT
        cost = max(1, int(cost))
        request_class = request_class or REQUEST_CLASS_ONLINE
        if request_class not in REQUEST_CLASSES:
            raise ValueError(f"unknown request_class {request_class!r} "
                             f"(want one of {REQUEST_CLASSES})")
        cfg = self.config
        if model:
            if cfg.model_costs:
                cost = max(1, int(cost * float(
                    cfg.model_costs.get(model, 1.0))))
            if cfg.model_priorities:
                priority += int(cfg.model_priorities.get(model, 0))
        try:
            # chaos: force the overload path on demand (error/drop -> a
            # synthetic rejection; delay -> a slow admission decision)
            try:
                if chaos.trip("serving.admission") == "drop":
                    raise chaos.ChaosError("injected admission drop")
            except chaos.ChaosError as e:
                raise AdmissionRejected(
                    "chaos", f"admission chaos: {e}",
                    retry_after_ms=self.config.min_retry_after_ms)
            ticket, waiter = self._admit_or_enqueue(tenant, cost, priority,
                                                    deadline, model,
                                                    request_class)
            if ticket is None:  # queued: wait for dispatch/shed/expiry
                ticket = self._wait(waiter, deadline)
        except AdmissionRejected as e:
            self._note_rejected(e, tenant, t0, trace_id)
            raise
        self._note_admitted(ticket, tenant, t0, trace_id)
        return ticket

    def _admit_or_enqueue(self, tenant: str, cost: int, priority: int,
                          deadline: Optional[Deadline], model: str = "",
                          request_class: str = REQUEST_CLASS_ONLINE):
        cfg = self.config
        batch = request_class == REQUEST_CLASS_BATCH
        with self._lock:
            # 1) rate limits fail fast — a bucket that says "not now" must
            # not convert rate limiting into queueing
            b = self._global_bucket
            if b is not None and not b.try_take():
                raise AdmissionRejected(
                    "global_rate", "global request rate exceeded",
                    retry_after_ms=max(cfg.min_retry_after_ms,
                                       int(b.retry_after_s() * 1e3)))
            if cfg.tenant_rate > 0:
                tb = self._tenant_buckets.get(tenant)
                if tb is None:
                    if len(self._tenant_buckets) >= cfg.tenant_bucket_cap:
                        stale = min(self._tenant_buckets,
                                    key=lambda t: self._tenant_buckets[t]._t)
                        del self._tenant_buckets[stale]
                    tb = self._tenant_buckets[tenant] = TokenBucket(
                        cfg.tenant_rate, cfg.tenant_burst)
                if not tb.try_take():
                    raise AdmissionRejected(
                        "tenant_rate",
                        f"tenant {tenant!r} request rate exceeded",
                        retry_after_ms=max(cfg.min_retry_after_ms,
                                           int(tb.retry_after_s() * 1e3)))
            # 2) fast path: capacity now, nobody queued ahead.  Batch
            # arrivals additionally clear the spare-capacity gate (idle
            # lane, unified headroom, arbiter floor) — the offline lane
            # soaks what online traffic is not using, never more
            if batch:
                if (self._inflight < cfg.max_inflight
                        and not len(self._batch_queue)
                        and self._batch_spare_locked(cost, model)):
                    self._inflight += 1
                    self.model_inflight[model] = (
                        self.model_inflight.get(model, 0) + 1)
                    self._note_pressure_locked()
                    return AdmissionTicket(
                        self, tenant, cost, 0.0, model,
                        request_class=REQUEST_CLASS_BATCH), None
            elif (self._inflight < cfg.max_inflight and not len(self._queue)
                    and self._capacity_ok_locked(cost, model)):
                self._inflight += 1
                self.model_inflight[model] = (
                    self.model_inflight.get(model, 0) + 1)
                self._note_pressure_locked()
                return AdmissionTicket(self, tenant, cost, 0.0, model), None
            q = self._batch_queue if batch else self._queue
            # 3) deadline-aware early rejection: don't queue a request
            # that cannot finish in time
            if deadline is not None:
                rem = deadline.remaining()
                predicted = self._predicted_wait_locked(len(q))
                if rem is not None and predicted > 0 and rem < predicted:
                    raise AdmissionRejected(
                        "deadline",
                        f"predicted queue wait {predicted * 1e3:.0f}ms "
                        f"exceeds remaining deadline {rem * 1e3:.0f}ms",
                        retry_after_ms=min(cfg.max_retry_after_ms,
                                           int(predicted * 1e3)))
            # 4) bounded queue with lowest-priority-first shedding.  Each
            # class sheds only within itself: a batch arrival can never
            # displace an online waiter, and an online overflow never
            # needs to — batch waiters occupy no online queue slot
            depth_cap = (cfg.max_batch_queue_depth
                         if batch and cfg.max_batch_queue_depth is not None
                         else cfg.max_queue_depth)
            if len(q) >= depth_cap:
                victim = q.peek_lowest_priority()
                if victim is None or victim.priority >= priority:
                    raise AdmissionRejected(
                        "queue_full",
                        f"admission {'batch ' if batch else ''}queue full "
                        f"(depth {len(q)})",
                        retry_after_ms=self._retry_hint_ms_locked())
                q.remove(victim)
                victim.reject = AdmissionRejected(
                    "shed",
                    f"shed for a priority-{priority} request "
                    f"(own priority {victim.priority})",
                    retry_after_ms=self._retry_hint_ms_locked())
                victim.event.set()
            # 5) deficit-round-robin fair queue (per class)
            self._seq += 1
            w = _Waiter(tenant, cost, priority, deadline, self._seq, model,
                        request_class=request_class)
            q.push(w)
            if not batch:
                self.peak_queue_depth = max(self.peak_queue_depth,
                                            len(self._queue))
            self._note_pressure_locked()
            return None, w

    def _wq(self, w: _Waiter) -> DeficitRoundRobinQueue:
        """The fair queue holding this waiter (per request class)."""
        return (self._batch_queue if w.request_class == REQUEST_CLASS_BATCH
                else self._queue)

    def _wait(self, w: _Waiter, deadline: Optional[Deadline]
              ) -> AdmissionTicket:
        """Block until dispatched, shed, timed out or past deadline.  The
        short poll doubles as a liveness re-dispatch: pages freed by the
        engine (not by a ticket release) still unblock the queue."""
        end = time.monotonic() + self.config.admit_wait_s
        while True:
            budget = end - time.monotonic()
            if deadline is not None:
                rem = deadline.remaining()
                if rem is not None:
                    budget = min(budget, rem)
            w.event.wait(timeout=max(0.0, min(0.05, budget)))
            with self._lock:
                if w.ticket is not None:
                    self._note_pressure_locked()
                    return w.ticket
                if w.reject is not None:
                    raise w.reject
                if deadline is not None and deadline.expired():
                    self._wq(w).remove(w)
                    self._note_pressure_locked()
                    raise AdmissionRejected(
                        "deadline", "deadline expired while queued",
                        retry_after_ms=0)
                if time.monotonic() >= end:
                    self._wq(w).remove(w)
                    self._note_pressure_locked()
                    raise AdmissionRejected(
                        "queue_timeout",
                        f"no capacity within "
                        f"{self.config.admit_wait_s:g}s",
                        retry_after_ms=self._retry_hint_ms_locked())
                self._dispatch_locked()

    def _dispatch_locked(self) -> None:
        """Move queued waiters into inflight while capacity allows, in
        DRR order.  A waiter the pool cannot hold yet goes back to the
        head (pages free continuously; the fairness charge is refunded).
        Batch-class waiters dispatch ONLY once no online waiter remains
        — and only into spare capacity — so the offline lane sits
        strictly below every online priority without sharing a queue."""
        while self._inflight < self.config.max_inflight and len(self._queue):
            w = self._queue.pop()
            if w.deadline is not None and w.deadline.expired():
                w.reject = AdmissionRejected(
                    "deadline", "deadline expired while queued",
                    retry_after_ms=0)
                w.event.set()
                continue
            if not self._capacity_ok_locked(w.cost, w.model):
                self._queue.requeue_front(w, refund=w.cost)
                break
            self._inflight += 1
            self.model_inflight[w.model] = (
                self.model_inflight.get(w.model, 0) + 1)
            w.ticket = AdmissionTicket(
                self, w.tenant, w.cost,
                time.perf_counter() - w.t_enqueue, w.model,
                drr_deficit=self._queue.deficit_of(w.tenant))
            w.event.set()
        while (self._inflight < self.config.max_inflight
               and len(self._batch_queue) and not len(self._queue)):
            w = self._batch_queue.pop()
            if w.deadline is not None and w.deadline.expired():
                w.reject = AdmissionRejected(
                    "deadline", "deadline expired while queued",
                    retry_after_ms=0)
                w.event.set()
                continue
            if not self._batch_spare_locked(w.cost, w.model):
                self._batch_queue.requeue_front(w, refund=w.cost)
                break
            self._inflight += 1
            self.model_inflight[w.model] = (
                self.model_inflight.get(w.model, 0) + 1)
            w.ticket = AdmissionTicket(
                self, w.tenant, w.cost,
                time.perf_counter() - w.t_enqueue, w.model,
                drr_deficit=self._batch_queue.deficit_of(w.tenant),
                request_class=REQUEST_CLASS_BATCH)
            w.event.set()

    def _on_release(self, ticket: AdmissionTicket) -> None:
        hold_s = time.perf_counter() - ticket._t_admit
        with self._lock:
            self._inflight -= 1
            n = self.model_inflight.get(ticket.model, 0) - 1
            if n > 0:
                self.model_inflight[ticket.model] = n
            else:
                self.model_inflight.pop(ticket.model, None)
            # EWMA of observed service time feeds the wait predictor
            self._service_ewma = (hold_s if self._service_ewma is None
                                  else 0.8 * self._service_ewma
                                  + 0.2 * hold_s)
            self._dispatch_locked()
            self._note_pressure_locked()

    # -- telemetry -----------------------------------------------------------
    def _note_pressure_locked(self) -> None:
        if self._metrics is not None:
            self._metrics.set_pressure(len(self._queue), self._inflight)

    def _note_admitted(self, ticket: AdmissionTicket, tenant: str,
                       t0: float, trace_id: Optional[str]) -> None:
        with self._lock:
            self.admitted_total += 1
            if ticket.request_class == REQUEST_CLASS_BATCH:
                # the offline lane NEVER feeds the queue-wait EWMA: the
                # fleet autoscaler scales on it, and preemptible filler
                # waiting for spare capacity must not buy replicas
                # (docs/SERVING.md "Offline batch lane")
                self.batch_admitted_total += 1
            else:
                w = ticket.queue_wait_s
                self._queue_wait_ewma = (w if self._queue_wait_ewma is None
                                         else 0.8 * self._queue_wait_ewma
                                         + 0.2 * w)
        if self._metrics is not None:
            self._metrics.note_admitted(tenant, ticket.queue_wait_s)
        if self.trace is not None:
            args = {"decision": "admit", "tenant": tenant}
            if trace_id:
                args["trace_id"] = trace_id
            self.trace.add_span("admission", t0,
                                time.perf_counter() - t0, **args)

    def _note_rejected(self, e: AdmissionRejected, tenant: str,
                       t0: float, trace_id: Optional[str]) -> None:
        with self._lock:
            self.rejected_total += 1
            self.rejected_by_reason[e.reason] = (
                self.rejected_by_reason.get(e.reason, 0) + 1)
            if e.reason == "shed":
                self.shed_total += 1
        if self._metrics is not None:
            self._metrics.note_rejected(e.reason, tenant)
        if self.trace is not None:
            args = {"decision": "reject", "reason": e.reason,
                    "tenant": tenant,
                    "retry_after_ms": e.retry_after_ms}
            if trace_id:
                args["trace_id"] = trace_id
            self.trace.add_span("admission", t0,
                                time.perf_counter() - t0, **args)
