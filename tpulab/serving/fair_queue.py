"""Deficit-round-robin fair queue over per-tenant FIFOs.

The admission queue (serving/admission.py) must not be a single FIFO: one
greedy tenant filling it turns every other tenant's requests into
tail-of-queue stragglers — the starvation mode the "millions of users"
north star makes routine, and the cost/fairness balancing the
adaptive-orchestration line in PAPERS.md argues a shared frontend needs.
Deficit round robin (Shreedhar & Varghese) gives cost-weighted fairness
with O(1) amortized work: each tenant owns a FIFO and a deficit counter;
visiting a tenant replenishes its deficit by ``quantum`` cost units, and
its head request is served once the deficit covers the request's cost.
Tenants submitting cheap requests therefore drain more of them per round;
tenants submitting expensive ones wait proportionally — but *every*
tenant is visited every round, so none starves.

Entries are any objects exposing ``tenant`` (str), ``cost`` (number, in
the same units as ``quantum`` — here estimated tokens), ``priority``
(int, higher = more important) and ``seq`` (int arrival order).  Within a
tenant, higher priority dequeues first (FIFO inside a class) — the same
ordering ContinuousBatcher applies post-admission.

NOT thread-safe: the caller (AdmissionController) holds its own lock.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional


class DeficitRoundRobinQueue:
    """Cost-weighted fair queue across tenants (module docstring)."""

    def __init__(self, quantum: int = 512):
        if quantum < 1:
            raise ValueError("quantum must be >= 1")
        self.quantum = int(quantum)
        self._fifos: Dict[str, Deque[object]] = {}
        self._ring: List[str] = []    # active-tenant rotation order
        self._cursor = 0              # next tenant to visit
        self._deficit: Dict[str, float] = {}
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def tenants(self) -> List[str]:
        return list(self._ring)

    def depths(self) -> Dict[str, int]:
        """Queued entries per tenant (the debugz live view)."""
        return {t: len(q) for t, q in self._fifos.items()}

    def deficit_of(self, tenant: str) -> float:
        """The tenant's carried DRR deficit (0.0 for idle tenants) —
        sampled into admission tickets so a wide event can say how much
        fair-queue credit the request's tenant held at dispatch."""
        return self._deficit.get(tenant, 0.0)

    def push(self, item) -> None:
        """Enqueue; higher ``priority`` jumps ahead within the tenant's
        FIFO (stable within a priority class)."""
        t = item.tenant
        q = self._fifos.get(t)
        if q is None:
            q = self._fifos[t] = deque()
            self._deficit[t] = 0.0
            # new tenants join BEHIND the cursor: they wait their turn in
            # the current round instead of jumping the rotation
            self._ring.insert(self._cursor, t)
            if len(self._ring) > 1:
                self._cursor = (self._cursor + 1) % len(self._ring)
        if q and item.priority > q[-1].priority:
            # rare path (priority inversions inside one tenant's backlog):
            # walk from the tail to keep the common FIFO append O(1)
            i = len(q)
            while i > 0 and q[i - 1].priority < item.priority:
                i -= 1
            q.insert(i, item)
        else:
            q.append(item)
        self._len += 1

    def _drop_tenant(self, tenant: str) -> None:
        i = self._ring.index(tenant)
        self._ring.pop(i)
        if i < self._cursor:
            self._cursor -= 1
        if self._ring:
            self._cursor %= len(self._ring)
        else:
            self._cursor = 0
        del self._fifos[tenant]
        del self._deficit[tenant]

    def pop(self) -> Optional[object]:
        """Next entry in DRR order (None when empty).  Terminates because
        every full rotation adds ``quantum`` to each non-empty tenant's
        deficit, so some head request becomes affordable."""
        if not self._len:
            return None
        while True:
            tenant = self._ring[self._cursor]
            q = self._fifos[tenant]
            head = q[0]
            deficit = self._deficit[tenant] + self.quantum
            if deficit >= head.cost:
                q.popleft()
                self._len -= 1
                if q:
                    # carry the surplus, but advance: one entry per visit
                    # keeps the rotation granularity; the carried deficit
                    # is what weights cheap-request tenants up
                    self._deficit[tenant] = deficit - head.cost
                    self._cursor = (self._cursor + 1) % len(self._ring)
                else:
                    self._drop_tenant(tenant)  # idle tenants keep no credit
                return head
            self._deficit[tenant] = deficit
            self._cursor = (self._cursor + 1) % len(self._ring)

    def requeue_front(self, item, refund: float = 0.0) -> None:
        """Put a popped entry back at the head of its tenant's FIFO (the
        dispatcher could not place it yet — e.g. the KV pool can't hold
        its cost).  ``refund`` restores the deficit the pop charged; a
        dropped tenant rejoins the ring at the cursor so it is visited
        next."""
        t = item.tenant
        q = self._fifos.get(t)
        if q is None:
            q = self._fifos[t] = deque()
            self._deficit[t] = 0.0
            self._ring.insert(self._cursor, t)
        q.appendleft(item)
        self._deficit[t] += refund
        self._len += 1

    def peek_lowest_priority(self) -> Optional[object]:
        """The shed candidate: globally lowest priority; ties broken by
        YOUNGEST arrival (largest seq) so the oldest request in a class
        keeps the progress it has paid queue time for."""
        worst = None
        for q in self._fifos.values():
            for item in q:
                if (worst is None or item.priority < worst.priority
                        or (item.priority == worst.priority
                            and item.seq > worst.seq)):
                    worst = item
        return worst

    def remove(self, item) -> bool:
        """Remove a specific entry (shed / wait-timeout / deadline expiry);
        False when it is no longer queued (raced with a pop)."""
        q = self._fifos.get(item.tenant)
        if q is None:
            return False
        try:
            q.remove(item)
        except ValueError:
            return False
        self._len -= 1
        if not q:
            self._drop_tenant(item.tenant)
        return True
