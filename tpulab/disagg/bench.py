"""The bench ``disagg`` row: ITL p99 + goodput under a prefill-heavy
trace, disaggregated prefill/decode vs one unified pool.

The workload is the disaggregation motivation made measurable: prompts
much longer than their decodes, arriving while earlier requests are
still decoding.  Unified, every arrival's prefill runs on the SAME
scheduler that owes the resident lanes their next token — decode ticks
stall behind prompt-sized forwards and ITL p99 blows up.  Disaggregated,
a prefill replica absorbs the prompt work and ships the finished KV over
the host tier's wire form; the decode replica admits by PROMOTING the
shipment (zero prefill dispatches) and its decode cadence never queues
behind a prefill.

On CPU jit the decode-replica ``prefill_dispatches == 0`` count and the
ITL tail RATIO are the signal; on-device every prefill removed from the
decode replica is a prompt-sized forward its resident lanes never stall
behind, so the p99 gap is the headline.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def benchmark_disagg(lanes: int = 2, n_requests: int = 8,
                     prompt_len: int = 48, steps: int = 8,
                     page_size: int = 8, d_model: int = 64,
                     n_heads: int = 4, n_layers: int = 2,
                     vocab: int = 256, dtype=None) -> Dict[str, Any]:
    """Run the prefill-heavy trace both ways and report per-mode ITL
    p50/p99 (seconds between consecutive streamed tokens, per lane),
    goodput (requests/s) and dispatch accounting, plus cross-mode token
    parity (greedy: the disaggregated stream must be bit-identical)."""
    import threading
    import time

    import jax.numpy as jnp

    from tpulab.disagg.shipper import KVShipper
    from tpulab.disagg.wire import prompt_digest
    from tpulab.engine.paged import ContinuousBatcher

    from tpulab.models.transformer import init_transformer_params

    dtype = dtype or jnp.float32
    max_len = prompt_len + steps + page_size
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(n_requests)]
    warm = rng.integers(0, vocab, (prompt_len,), np.int32)

    def make_cb():
        return ContinuousBatcher(
            params, n_heads=n_heads, n_layers=n_layers, lanes=lanes,
            max_len=max_len, page_size=page_size, compute_dtype=dtype,
            kv_offload=True)

    def run_trace(submit_one, warmup):
        """Drive all requests concurrently; per-request token timestamps
        feed the ITL distribution."""
        warmup()
        stamps = [[] for _ in prompts]
        tokens = [None] * len(prompts)
        threads = []
        t0 = time.perf_counter()

        def one(i):
            tokens[i] = submit_one(
                i, lambda _t, _j, i=i: stamps[i].append(
                    time.perf_counter()))

        for i in range(len(prompts)):
            threads.append(threading.Thread(target=one, args=(i,)))
            threads[-1].start()
        for th in threads:
            th.join(timeout=600)
        wall = max(1e-6, time.perf_counter() - t0)
        gaps = [b - a for ts in stamps for a, b in zip(ts, ts[1:])]
        entry = {
            "goodput_rps": round(len(prompts) / wall, 2),
            "wall_s": round(wall, 3),
            "itl_ms_p50": round(1e3 * float(np.percentile(gaps, 50)), 2),
            "itl_ms_p99": round(1e3 * float(np.percentile(gaps, 99)), 2),
        }
        return entry, tokens

    # -- unified: one pool serves prefill AND decode -------------------------
    def unified() -> Dict[str, Any]:
        cb = make_cb()
        try:
            entry, tokens = run_trace(
                lambda i, cb_tok: list(cb.submit(
                    prompts[i], steps, on_token=cb_tok).result(timeout=600)),
                lambda: cb.submit(warm, steps).result(timeout=600))
            entry["prefill_dispatches"] = cb.prefill_dispatches
            return entry, tokens
        finally:
            cb.shutdown()

    # -- disaggregated: prefill replica -> wire -> decode replica ------------
    def disagg() -> Dict[str, Any]:
        bp, bd = make_cb(), make_cb()
        ship_out, ship_in = KVShipper(bp.kv_offload), KVShipper(bd.kv_offload)
        try:
            pf0 = [0]

            def warmup():
                bp.submit(warm, 1).result(timeout=600)
                bd.submit(warm, steps).result(timeout=600)
                pf0[0] = bd.prefill_dispatches  # post-warm baseline

            def one(i, cb_tok):
                dig = prompt_digest(prompts[i])
                fut = bp.submit(prompts[i], 1, export_digest=dig)
                first = fut.result(timeout=600)[0]
                blob = ship_out.export(
                    getattr(fut, "_tpulab_kv_export", None),
                    digest=dig, first_token=first)
                ship = (ship_in.import_shipment(blob)
                        if blob is not None else None)
                if ship is not None:
                    f2 = bd.submit_shipped(prompts[i], steps, first,
                                           ship.handle, on_token=cb_tok)
                else:  # lost shipment: decode replica prefills locally
                    f2 = bd.submit(prompts[i], steps, on_token=cb_tok)
                return list(f2.result(timeout=600))

            entry, tokens = run_trace(one, warmup)
            entry.update(
                decode_prefill_dispatches=bd.prefill_dispatches - pf0[0],
                shipments=ship_out.exports,
                ship_failures=(ship_out.export_failures
                               + ship_in.import_failures),
                ship_mb=round(ship_out.bytes_out / 2**20, 2))
            return entry, tokens
        finally:
            bp.shutdown()
            bd.shutdown()

    u_entry, u_tokens = unified()
    d_entry, d_tokens = disagg()
    return {
        "lanes": lanes, "n_requests": n_requests,
        "prompt_len": prompt_len, "steps": steps,
        "unified": u_entry, "disagg": d_entry,
        "token_parity": u_tokens == d_tokens,
    }
