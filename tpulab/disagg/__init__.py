"""tpulab.disagg — disaggregated prefill/decode: replica roles with KV
shipping over the host tier.

Prefill is compute-bound and bursty; decode is latency-bound and steady
— serving both from one paged pool wrecks ITL p99 under prefill bursts
(docs/SERVING.md "Replica roles", docs/PERFORMANCE.md).  This package
turns the tiered-KV swap path (tpulab.kvcache) into a wire: a prefill
replica runs the prompt forward only and demotes the finished KV to the
host tier in **wire form**; a decode replica admits the request by
**promoting the shipped KV** through ``KVOffloadManager.restore`` — zero
prefill dispatches on the decode side, bit-identical tokens.

- :mod:`~tpulab.disagg.wire` — versioned, CRC-checked snapshot encoding
  (:func:`serialize_snapshot` / :func:`deserialize_snapshot`,
  :class:`WireFormatError`, :func:`prompt_digest`).  Mismatched replicas
  (dtype / layout / page size / version) reject instead of corrupt.
- :class:`~tpulab.disagg.shipper.KVShipper` — export on the prefill
  replica (write-behind fence included), import + geometry validation on
  the decode replica.  ``disagg.ship`` chaos point on both sides; every
  failure degrades to local prefill on the decode replica.
- :func:`~tpulab.disagg.bench.benchmark_disagg` — the ``bench.py
  disagg`` row: ITL p99 + goodput, disaggregated vs unified, under a
  prefill-heavy trace.

Serving wire-up: ``mgr.serve(role="prefill"|"decode"|"unified", ...)``
reports the role over the Status RPC;
``GenerationReplicaSet(disaggregate=True)`` routes new requests to
prefill replicas and hands the shipment to a decode replica picked by
the existing admission load gauges.
"""

from tpulab.disagg.bench import benchmark_disagg  # noqa: F401
from tpulab.disagg.shipper import KVShipper, ShippedKV  # noqa: F401
from tpulab.disagg.wire import (WireFormatError,  # noqa: F401
                                deserialize_snapshot, prompt_digest,
                                serialize_snapshot)

__all__ = ["KVShipper", "ShippedKV", "WireFormatError",
           "serialize_snapshot", "deserialize_snapshot", "prompt_digest",
           "benchmark_disagg"]
