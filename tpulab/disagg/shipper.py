"""KVShipper: exports finished prefills to wire form and imports
shipments on decode replicas.

One shipper wraps one :class:`~tpulab.kvcache.offload.KVOffloadManager`
(hence one pool / one host tier) and is the ONLY disaggregation code
that touches KV bytes:

- **export** (prefill replica): waits out the write-behind fence of the
  export handle the engine produced (``submit(export_digest=...)``),
  pops the snapshot from the host tier and wire-encodes it.  The wait IS
  the drain fence — a shipment is never serialized from a snapshot still
  in flight.
- **import** (decode replica): decodes + CRC-checks the wire payload,
  validates its geometry against the LOCAL pool (dtype, page size, layer
  count, head layout — mismatched replicas reject, never corrupt), lands
  it in the local host tier and mints the resident
  :class:`~tpulab.kvcache.offload.SwapHandle` that
  ``ContinuousBatcher.submit_shipped`` promotes through the existing
  ``KVOffloadManager.restore`` path.

Every failure on either side returns ``None`` (after counting) — the
degradation is always "as if no shipment existed": the decode replica
prefills locally, the request is never stuck and a lane is never
corrupted.  The ``disagg.ship`` chaos point (docs/ROBUSTNESS.md) trips
on both sides to prove it.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

from tpulab import chaos
from tpulab.disagg.wire import (WireFormatError, deserialize_snapshot,
                                serialize_snapshot)

log = logging.getLogger("tpulab.disagg")


class ShippedKV:
    """One imported shipment, ready to admit: the resident host-tier
    handle plus the metadata the decode lane needs."""

    __slots__ = ("handle", "digest", "length", "first_token", "nbytes")

    def __init__(self, handle, digest: bytes, length: int,
                 first_token: int, nbytes: int):
        self.handle = handle
        self.digest = digest
        self.length = length
        self.first_token = first_token
        self.nbytes = nbytes


class KVShipper:
    """Wire-format export/import over one KVOffloadManager (module
    docstring)."""

    #: bound on waiting for an export's write-behind snapshot to land
    EXPORT_WAIT_S = 10.0

    def __init__(self, manager):
        self.manager = manager
        self._lock = threading.Lock()
        self._seq = 0
        # -- counters (observability / test assertions) ----------------------
        self.exports = 0           # shipments serialized
        self.imports = 0           # shipments admitted into the host tier
        self.export_failures = 0   # export degraded (nothing shipped)
        self.import_failures = 0   # import rejected/degraded
        self.bytes_out = 0
        self.bytes_in = 0

    # -- prefill side ---------------------------------------------------------
    def export(self, handle, *, digest: bytes, first_token: int,
               timeout: Optional[float] = None) -> Optional[bytes]:
        """Wire-encode the finished prefill behind ``handle``.  None =
        degraded (chaos / snapshot dropped / evicted): the caller ships
        nothing and the decode side prefills locally."""
        try:
            if chaos.trip("disagg.ship") == "drop":
                raise chaos.ChaosError("injected shipment drop")
            if handle is None:
                raise WireFormatError("no export snapshot (swap degraded)")
            arr = self.manager.take_snapshot(
                handle, self.EXPORT_WAIT_S if timeout is None else timeout)
            if arr is None:
                raise WireFormatError("export snapshot unavailable")
            blob = serialize_snapshot(
                arr, digest=digest, length=handle.length,
                page_size=self.manager.pool.page_size,
                first_token=first_token)
        except Exception as e:  # noqa: BLE001 - degrade, never corrupt
            self.export_failures += 1
            log.warning("KV export degraded (decode side will prefill "
                        "locally): %s: %s", type(e).__name__, str(e)[:200])
            return None
        self.exports += 1
        self.bytes_out += len(blob)
        return blob

    # -- decode side ----------------------------------------------------------
    def import_shipment(self, blob: bytes) -> Optional[ShippedKV]:
        """Admit a wire shipment into the LOCAL host tier.  None =
        rejected (corrupt payload, geometry mismatch, budget refusal,
        chaos) — the caller degrades to local prefill."""
        try:
            if chaos.trip("disagg.ship") == "drop":
                raise chaos.ChaosError("injected shipment drop")
            arr, header = deserialize_snapshot(blob)
            self._check_geometry(arr, header)
        except Exception as e:  # noqa: BLE001 - degrade, never corrupt
            self.import_failures += 1
            log.warning("KV import rejected (degrading to local prefill): "
                        "%s: %s", type(e).__name__, str(e)[:200])
            return None
        with self._lock:
            self._seq += 1
            key = ("shipin", self._seq)
        handle = self.manager.adopt(key, arr, header["length"])
        if handle is None:  # budget refused (already counted as swap_drop)
            self.import_failures += 1
            return None
        self.imports += 1
        self.bytes_in += len(blob)
        return ShippedKV(handle, header["digest"], header["length"],
                         header["first_token"], len(blob))

    def discard(self, ship: ShippedKV) -> None:
        """Drop an imported-but-unadmittable shipment (engine rejected
        the lane setup) so it stops holding host-tier budget."""
        self.manager.discard(ship.handle)

    def check_geometry(self, arr: np.ndarray, header: dict) -> None:
        """Public face of the reject-don't-corrupt gate — every OTHER
        path that admits foreign KV bytes into this pool (the fleet KV
        fabric's pull, tpulab.kvfabric) must run the SAME validation as
        a disagg import; re-deriving it per consumer is how one of them
        silently corrupts a pool.  Raises :class:`WireFormatError`."""
        self._check_geometry(arr, header)

    def _check_geometry(self, arr: np.ndarray, header: dict) -> None:
        """The reject-don't-corrupt gate: the shipment's layout must
        match the local pool axis for axis (page count excepted)."""
        pool = self.manager.pool
        local = tuple(pool.kv.shape)       # (L, P, 2, S, Hkv, D)
        if arr.ndim != len(local):
            raise WireFormatError(
                f"shipment rank {arr.ndim} != pool rank {len(local)}")
        ship_geo = arr.shape[:1] + arr.shape[2:]
        local_geo = local[:1] + local[2:]
        if ship_geo != local_geo:
            raise WireFormatError(
                f"shipment geometry {ship_geo} != pool {local_geo} "
                "(layer/page-size/head layout mismatch)")
        if np.dtype(arr.dtype) != np.dtype(pool.dtype):
            raise WireFormatError(
                f"shipment dtype {arr.dtype} != pool dtype "
                f"{np.dtype(pool.dtype).name}")
        if int(header["page_size"]) != int(pool.page_size):
            raise WireFormatError(
                f"shipment page_size {header['page_size']} != pool "
                f"{pool.page_size}")
        n = int(arr.shape[1])
        length = int(header["length"])
        if length <= 0 or length > n * pool.page_size:
            raise WireFormatError(
                f"shipment length {length} outside (0, "
                f"{n * pool.page_size}] for {n} pages")
