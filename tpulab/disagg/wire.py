"""KV-snapshot wire format: the serialized form of a HostKVStore entry.

A shipment is one finished prefill's KV pages — the ``(L, n_pages, 2, S,
Hkv, D)`` page-granular snapshot the host tier already holds — plus the
metadata a decode replica needs to admit the request without prefilling:
the prompt digest, the covered length, and the first token (picked at
prefill time on the prefill replica, so the decode replica never fetches
prefill logits).

Layout (all little-endian)::

    b"TPKV" | version u16 | header_len u32 | header (JSON, utf-8)
           | payload_crc32 u32 | payload (C-contiguous array bytes)

The JSON header carries ``dtype``, ``shape``, ``page_size``, ``length``,
``digest`` (hex), ``first_token`` and optional extras — versioned and
self-describing, so a decode replica with a DIFFERENT pool geometry
(dtype / page size / layer count / head layout) **rejects** the shipment
(:class:`WireFormatError`) instead of scattering foreign bytes into its
pool.  The CRC32 covers the payload: a corrupted shipment is detected at
import, never promoted into a lane.

Degradation contract (docs/ROBUSTNESS.md): every rejection here is
recoverable — the decode replica simply prefills locally, exactly as if
no shipment had arrived.
"""

from __future__ import annotations

import hashlib
import json
import struct
import zlib
from typing import Any, Dict, Tuple

import numpy as np

MAGIC = b"TPKV"
VERSION = 1

_HEAD = struct.Struct("<HI")   # version, header_len
_CRC = struct.Struct("<I")


class WireFormatError(ValueError):
    """The shipment cannot be admitted: bad magic, unknown version,
    malformed header, geometry mismatch, or payload corruption.  Callers
    treat it as a LOST shipment (degrade to local prefill), never as a
    reason to touch the pool."""


def prompt_digest(prompt) -> bytes:
    """The shipment identity: a 16-byte blake2b over the prompt's int32
    token bytes — the same digest family the prefix cache keys on."""
    raw = np.ascontiguousarray(np.asarray(prompt, np.int32).reshape(-1))
    return hashlib.blake2b(raw.tobytes(), digest_size=16).digest()


def serialize_snapshot(array: np.ndarray, *, digest: bytes, length: int,
                       page_size: int, first_token: int,
                       extras: Dict[str, Any] = None) -> bytes:
    """Wire-encode one host-tier KV snapshot (module docstring layout).

    ``array`` is the page-granular snapshot ``(L, n, 2, S, Hkv, D)``;
    ``length`` the token positions it covers; ``first_token`` the prefill
    replica's first-token pick (emitted as index 0 downstream)."""
    array = np.ascontiguousarray(array)
    header = {
        "dtype": array.dtype.name,
        "shape": [int(d) for d in array.shape],
        "page_size": int(page_size),
        "length": int(length),
        "digest": bytes(digest).hex(),
        "first_token": int(first_token),
    }
    if extras:
        header.update(extras)
    hdr = json.dumps(header, sort_keys=True).encode("utf-8")
    payload = array.tobytes()
    return b"".join([MAGIC, _HEAD.pack(VERSION, len(hdr)), hdr,
                     _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF), payload])


def deserialize_snapshot(blob: bytes) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Decode a shipment -> ``(array, header)``.  Raises
    :class:`WireFormatError` on anything that would admit garbage: bad
    magic, version skew, truncation, or a CRC mismatch."""
    blob = bytes(blob)
    base = len(MAGIC) + _HEAD.size
    if len(blob) < base or blob[:len(MAGIC)] != MAGIC:
        raise WireFormatError("not a KV shipment (bad magic)")
    version, hdr_len = _HEAD.unpack_from(blob, len(MAGIC))
    if version != VERSION:
        raise WireFormatError(
            f"shipment version {version} != {VERSION} (mismatched "
            "replicas must reject, not corrupt)")
    if len(blob) < base + hdr_len + _CRC.size:
        raise WireFormatError("truncated shipment header")
    try:
        header = json.loads(blob[base:base + hdr_len].decode("utf-8"))
        dtype = np.dtype(header["dtype"])
        shape = tuple(int(d) for d in header["shape"])
        header["digest"] = bytes.fromhex(header["digest"])
    except WireFormatError:
        raise
    except Exception as e:  # noqa: BLE001 - malformed header = reject
        raise WireFormatError(f"malformed shipment header: {e}") from e
    (crc,) = _CRC.unpack_from(blob, base + hdr_len)
    payload = blob[base + hdr_len + _CRC.size:]
    want = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
    if len(payload) != want:
        raise WireFormatError(
            f"payload size {len(payload)} != header-declared {want}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise WireFormatError("shipment payload corrupt (CRC mismatch)")
    return np.frombuffer(payload, dtype=dtype).reshape(shape).copy(), header
