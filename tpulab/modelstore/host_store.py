"""Host-memory parameter tier: budgeted, LRU store for weight pytrees.

The multi-model serving mode (:mod:`tpulab.modelstore`) keeps only the
*hot* models' weights in HBM; every other registered model's parameters
live here — host RAM, budgeted, LRU — exactly the tier
:class:`~tpulab.kvcache.host_store.HostKVStore` provides for KV pages,
generalized from one opaque array per key to a whole parameter pytree
(transformer layer dicts, quantized ``{"w_int8", "scale"}`` leaves, ONNX
import trees — any structure ``jax.tree_util`` can flatten).

Storage mirrors ``HostKVStore`` deliberately: every leaf owns a
:class:`~tpulab.memory.descriptor.Descriptor` from a host ``IAllocator``
(default: the mmap-backed
:class:`~tpulab.memory.raw_allocators.MallocAllocator` behind the
``make_allocator`` facade) and is written through the descriptor's
zero-copy numpy view; ``get``/``pop`` return *copies* assembled back into
the original treedef — an LRU eviction from another thread closes the
backing mapping, and a zero-copy view must not outlive it
(copy-on-get).  All *policy* (which model to demote, when to promote)
lives in :class:`~tpulab.modelstore.multiplexer.WeightMultiplexer`.

Thread safety: one lock — the TransferEngine collector thread lands
swap-outs here while acquire paths read/pop.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

import numpy as np

from tpulab.memory.allocator import make_allocator
from tpulab.memory.raw_allocators import MallocAllocator

#: default host-tier budget for cold weights (bytes)
DEFAULT_HOST_BUDGET = 1 << 30


def tree_nbytes(tree: Any) -> int:
    """Total leaf bytes of a parameter pytree (counting quantized leaves
    at their stored width)."""
    import jax
    return sum(np.dtype(leaf.dtype).itemsize * int(np.prod(leaf.shape))
               for leaf in jax.tree_util.tree_leaves(tree)
               if hasattr(leaf, "shape"))


class _Leaf:
    __slots__ = ("desc", "shape", "dtype")

    def __init__(self, desc, shape: Tuple[int, ...], dtype):
        self.desc = desc
        self.shape = shape
        self.dtype = dtype


class _Entry:
    __slots__ = ("leaves", "treedef", "nbytes")

    def __init__(self, leaves: List[_Leaf], treedef, nbytes: int):
        self.leaves = leaves
        self.treedef = treedef
        self.nbytes = nbytes

    def release(self) -> None:
        for leaf in self.leaves:
            leaf.desc.release()


class HostParamStore:
    """Budgeted LRU store for model parameter pytrees (module docstring).

    ``budget_bytes`` caps resident parameter bytes; inserting past it
    evicts cold models first, and a single model larger than the whole
    budget is refused (``put`` returns False — the caller's lost-weights
    path: the next swap-in does a cold rebuild instead).
    """

    def __init__(self, budget_bytes: int = DEFAULT_HOST_BUDGET,
                 allocator=None):
        if budget_bytes <= 0:
            raise ValueError("budget_bytes must be > 0")
        self.budget_bytes = int(budget_bytes)
        self._alloc = allocator or make_allocator(MallocAllocator())
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        # -- counters (poll-advanced by ModelStoreMetrics) ------------------
        self.puts = 0          # param trees stored
        self.hits = 0          # get/pop found the key
        self.misses = 0        # get/pop did not
        self.evictions = 0     # LRU models pushed out by budget pressure
        self.drops = 0         # param trees refused (larger than budget)

    # -- sizing --------------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def headroom_bytes(self) -> int:
        """Bytes storable right now WITHOUT evicting."""
        with self._lock:
            return max(0, self.budget_bytes - self._bytes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def keys(self) -> List[Any]:
        """Resident keys, coldest first (the Status RPC's host-tier
        model list)."""
        with self._lock:
            return list(self._entries)

    # -- the tier ------------------------------------------------------------
    def put(self, key, tree: Any) -> bool:
        """Store the parameter pytree under ``key`` (replacing any
        incumbent), evicting LRU entries until it fits.  False = refused
        (the tree exceeds the whole budget) — the model is simply NOT in
        the tier and its next swap-in cold-rebuilds."""
        import jax
        raw, treedef = jax.tree_util.tree_flatten(tree)
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in raw]
        nbytes = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            if nbytes > self.budget_bytes:
                self.drops += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                old.release()
            while self._bytes + nbytes > self.budget_bytes and self._entries:
                _, cold = self._entries.popitem(last=False)
                self._bytes -= cold.nbytes
                cold.release()
                self.evictions += 1
            leaves = []
            for a in arrays:
                desc = self._alloc.allocate_descriptor(max(1, int(a.nbytes)))
                desc.numpy(a.dtype, a.shape)[...] = a
                leaves.append(_Leaf(desc, a.shape, a.dtype))
            self._entries[key] = _Entry(leaves, treedef, nbytes)
            self._bytes += nbytes
            self.puts += 1
            return True

    def _assemble(self, e: _Entry) -> Any:
        import jax
        arrays = [leaf.desc.numpy(leaf.dtype, leaf.shape).copy()
                  for leaf in e.leaves]
        return jax.tree_util.tree_unflatten(e.treedef, arrays)

    def get(self, key) -> Optional[Any]:
        """A COPY of the param tree (and an LRU touch), or None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return self._assemble(e)

    def pop(self, key) -> Optional[Any]:
        """``get`` + remove — the swap-in read (a model is in exactly one
        tier at a time: promoting it to HBM removes the host copy; the
        eviction path writes it back)."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                self.misses += 1
                return None
            self._bytes -= e.nbytes
            self.hits += 1
            tree = self._assemble(e)
            e.release()
            return tree

    def remove(self, key) -> bool:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return False
            self._bytes -= e.nbytes
            e.release()
            return True

    def clear(self) -> None:
        with self._lock:
            for e in self._entries.values():
                e.release()
            self._entries.clear()
            self._bytes = 0
