"""Multi-model serving: weight multiplexing over the host tier.

trtlab's v1 ``InferenceManager`` serves many models from pooled device
resources (PAPER.md §0); this package is that capability on the tpulab
memory framework: N registered models (LLM + ViT/ResNet + ONNX imports,
quantized variants) share one device's HBM, with cold weights in the
budgeted host tier (:class:`HostParamStore`, on the tpulab.memory
allocator/descriptor framework like the KV tier) and hot models swapped
in/out by :class:`WeightMultiplexer` over the same write-behind
TransferEngine path the KV offload manager uses.  docs/SERVING.md
"Multi-model serving" is the operator view.
"""

from tpulab.modelstore.host_store import (DEFAULT_HOST_BUDGET,
                                          HostParamStore, tree_nbytes)
from tpulab.modelstore.multiplexer import (BatcherAdapter,
                                           CompiledModelAdapter, ModelLease,
                                           WeightMultiplexer,
                                           benchmark_multi_model)

__all__ = [
    "DEFAULT_HOST_BUDGET",
    "HostParamStore",
    "tree_nbytes",
    "BatcherAdapter",
    "CompiledModelAdapter",
    "ModelLease",
    "WeightMultiplexer",
    "benchmark_multi_model",
]
