"""Weight multiplexer: N models time-share one device's HBM.

trtlab's v1 ``InferenceManager`` serves *many models* from pooled device
resources; tpulab bound one model per process until now.  This module is
the registry-driven multi-model serving mode: every registered model's
parameters live in exactly ONE tier at a time — **hot** (HBM, byte-
accurately accounted against ``hbm_budget_bytes``, next to the
``PagedKVPool`` pages the same device holds) or **cold** (the budgeted
host tier, :class:`~tpulab.modelstore.host_store.HostParamStore`) — and
the :class:`WeightMultiplexer` moves them between tiers on demand:

- **Swap-out** (eviction) rides the same write-behind
  :class:`~tpulab.tpu.transfer.TransferEngine` path the KV tier uses:
  the device→host fetch lands on the collector thread, HBM accounting
  releases only when the copy is resident, and acquirers waiting for
  headroom are woken then — never a torn copy, never double-freed HBM.
- **Swap-in** pops the host copy and re-places it via the entry's own
  placement path (``jax.device_put`` onto the adapter's recorded device
  or sharding tree — a TP-sharded LLM and replicated small models
  coexist; the restore is mesh-aware exactly like the KV tier's
  placement-keyed scatter).  Promoted params are bit-identical to the
  bytes that left the device, test-enforced against a fresh build.
- **Degradation** (``modelstore.swap`` chaos point, transfer failures,
  host-budget refusals): a failed swap-out loses the snapshot — the
  model is LOST and its next acquire does a **cold rebuild** through the
  registered builder; a failed swap-in discards the host copy and
  rebuilds in place.  Every degraded path serves correct (rebuilt)
  weights; a corrupt serve is structurally impossible because attach
  only ever sees freshly fetched host bytes or a fresh build.

Pinning & working-set protection: an acquired lease is a refcount —
models with live leases (a decode stream mid-flight, an Infer RPC in the
runner) are NEVER eviction candidates, so a burst on model A cannot
evict model B's working set mid-decode; ``pinned=True`` models are
permanently resident.  The admission frontend reads
:meth:`WeightMultiplexer.can_admit` so requests for a model that cannot
be made resident *right now* queue instead of thrashing the hot set.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tpulab import chaos
from tpulab.modelstore.host_store import (DEFAULT_HOST_BUDGET,
                                          HostParamStore, tree_nbytes)

log = logging.getLogger("tpulab.modelstore")

#: entry states (a model is in exactly one)
_HOT = "hot"                 # params resident in HBM, servable
_COLD = "cold"               # params resident in the host tier
_LOST = "lost"               # params in NO tier: next acquire cold-rebuilds
_SWAP_IN = "swapping_in"     # claimed by an acquire, attach in progress
_SWAP_OUT = "swapping_out"   # write-behind device->host copy in flight


class ModelLease:
    """One request's hold on a hot model (a refcount, not a lock): the
    model cannot be evicted while any lease is live.  Context manager;
    ``release()`` is idempotent."""

    __slots__ = ("name", "_mux", "_entry", "_released")

    def __init__(self, mux: "WeightMultiplexer", entry: "_ModelEntry"):
        self.name = entry.name
        self._mux = mux
        self._entry = entry
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._mux._release(self._entry)

    def __enter__(self) -> "ModelLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _ModelEntry:
    __slots__ = ("name", "adapter", "nbytes", "pinned", "state", "refs")

    def __init__(self, name: str, adapter, nbytes: int, pinned: bool,
                 state: str):
        self.name = name
        self.adapter = adapter
        self.nbytes = int(nbytes)
        self.pinned = bool(pinned)
        self.state = state
        self.refs = 0


# -- adapters ----------------------------------------------------------------
class CompiledModelAdapter:
    """Multiplexes a dense :class:`~tpulab.engine.runtime.CompiledModel`
    (the Infer RPC path).  Weights re-place through the model's tracked
    device allocator (``allocate_tree``) so the framework HBM gauge and
    the multiplexer agree byte for byte; the executables themselves stay
    compiled across swaps — they take params as arguments, so a swap-in
    never recompiles.

    ``builder`` (e.g. ``lambda: registry.build_model(name)``) is the
    cold-rebuild path; when given, the Model's own host param reference
    is dropped so the budgeted host tier holds the only host copy."""

    def __init__(self, compiled, builder: Optional[Callable] = None):
        self.compiled = compiled
        self._builder = builder
        if builder is not None:
            # the budgeted tier is the host copy now; rebuilds re-derive
            compiled.model.params = None

    def resident(self) -> bool:
        return self.compiled.device_params is not None

    def param_bytes(self) -> int:
        src = (self.compiled.device_params
               if self.compiled.device_params is not None
               else self.compiled.model.params)
        return tree_nbytes(src)

    def busy(self) -> bool:
        return False  # in-flight Infer RPCs hold leases; nothing else runs

    def detach(self):
        return self.compiled.device_params

    def on_detached(self) -> None:
        self.compiled.release_weights()

    def attach(self, host_tree) -> None:
        import jax
        c = self.compiled
        if c.allocator is not None:
            c.weights_addr, c.device_params = c.allocator.allocate_tree(
                host_tree)
        else:  # pragma: no cover - untracked CompiledModel
            c.device_params = jax.device_put(host_tree, c.device)

    def rebuild(self):
        if self._builder is not None:
            return self._builder().params
        if self.compiled.model.params is not None:
            return self.compiled.model.params
        raise RuntimeError(
            f"model {self.compiled.model.name!r}: weights lost from every "
            "tier and no builder registered for a cold rebuild")


class BatcherAdapter:
    """Multiplexes a :class:`~tpulab.engine.paged.ContinuousBatcher`'s
    target params (the Generate RPC path).  The batcher's fused programs
    take params as jit *arguments*, so attach/detach is pure placement —
    ``device_put`` onto the batcher's recorded placement (the Megatron-TP
    sharding tree under a mesh, the pool device otherwise): a swap-in
    restores a TP-sharded LLM onto its mesh bit-exactly.

    Eviction safety: a batcher with active lanes or queued work refuses
    to detach (``busy()``), independently of the lease refcount — the
    hard floor under "a decode-in-flight model is never evicted"."""

    def __init__(self, batcher, builder: Optional[Callable] = None):
        self.batcher = batcher
        self._builder = builder
        sh = getattr(batcher, "_param_sh", None)
        self._placement = sh if sh is not None else batcher.pool.device

    def resident(self) -> bool:
        return self.batcher.params is not None

    def param_bytes(self) -> int:
        return tree_nbytes(self.batcher.params)

    def busy(self) -> bool:
        b = self.batcher
        return (int(getattr(b, "active_lanes", 0)) > 0
                or int(getattr(b, "queued_requests", 0)) > 0)

    def detach(self):
        if self.busy():
            raise RuntimeError("batcher has in-flight work; refusing to "
                               "detach its weights")
        dev = self.batcher.params
        self.batcher.params = None
        return dev

    def on_detached(self) -> None:
        pass  # device buffers free when the fetch drops its reference

    def attach(self, host_tree) -> None:
        import jax
        self.batcher.params = jax.device_put(host_tree, self._placement)

    def rebuild(self):
        if self._builder is None:
            raise RuntimeError(
                "batcher weights lost from every tier and no builder "
                "registered for a cold rebuild")
        built = self._builder()
        # accept either a raw param tree or a Model-like with .params
        return getattr(built, "params", built)


class WeightMultiplexer:
    """Hot-set manager over one device's weight HBM (module docstring).

    ``hbm_budget_bytes`` caps combined hot-model weight bytes (KV pools /
    activations are outside it — size it at what's left after the pools);
    ``store`` / ``host_budget_bytes`` configure the cold tier;
    ``transfer`` optionally shares a TransferEngine; ``metrics`` an
    optional :class:`~tpulab.utils.metrics.ModelStoreMetrics`."""

    #: default bound on how long an acquire waits for headroom (models
    #: with live leases never evict — a long decode can hold this long)
    ACQUIRE_TIMEOUT_S = 120.0

    def __init__(self, hbm_budget_bytes: int,
                 store: Optional[HostParamStore] = None,
                 host_budget_bytes: int = DEFAULT_HOST_BUDGET,
                 transfer=None, metrics=None, hbm=None):
        if hbm_budget_bytes <= 0:
            raise ValueError("hbm_budget_bytes must be > 0")
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        # unified HBM economy (tpulab.hbm): with an arbiter this store is
        # the WEIGHTS tenant — acquires for a cold model request bytes
        # through the pressure protocol (which may demote idle KV), a KV
        # burst may press cold unleased models out, and every byte the
        # internal accounting holds is mirrored as a ledger claim.  A
        # denied request degrades to the static hbm_budget_bytes path —
        # exactly the pre-arbiter behavior.
        self._hbm = hbm
        if hbm is not None:
            from tpulab.hbm import WEIGHTS_TENANT
            self._hbm_tenant = WEIGHTS_TENANT
            hbm.register(WEIGHTS_TENANT, reclaim=self._hbm_reclaim,
                         reclaimable=self._hbm_evictable_bytes,
                         gauge=lambda: self.hbm_bytes_in_use)
        # identity check, not truthiness (an empty store is falsy)
        self.store = store if store is not None \
            else HostParamStore(host_budget_bytes)
        if transfer is None:
            from tpulab.tpu.transfer import TransferEngine
            transfer = TransferEngine(name="wswap")
            self._owns_transfer = True
        else:
            self._owns_transfer = False
        self._transfer = transfer
        self.metrics = metrics
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._entries: "OrderedDict[str, _ModelEntry]" = OrderedDict()
        self._hbm_bytes = 0          # hot + both swap directions (reserved)
        self._pending_ops = 0        # write-behind copies still in flight
        self._pending_out_bytes = 0  # HBM that frees when those copies land
        # -- counters (ModelStoreMetrics.poll advances from these) ----------
        self.swap_ins = 0            # host->device promotions served
        self.swap_outs = 0           # device->host snapshots landed
        self.swap_in_bytes = 0
        self.swap_out_bytes = 0
        self.evictions = 0           # swap-outs initiated by HBM pressure
        self.cold_rebuilds = 0       # acquires served by a fresh build
        self.swap_failures = 0       # chaos/transfer degradations
        self.swap_drops = 0          # host-budget-refused snapshots

    # -- registration --------------------------------------------------------
    def register(self, name: str, adapter, pinned: bool = False,
                 params: Any = None) -> None:
        """Register a servable under ``name``.  A resident adapter enters
        HOT (trimming colder idle models to budget, write-behind); a
        non-resident one enters COLD when ``params`` (its host tree) is
        given, else LOST — its first acquire cold-rebuilds."""
        with self._cv:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            resident = bool(adapter.resident())
            nbytes = int(adapter.param_bytes()) if resident \
                else int(tree_nbytes(params)) if params is not None else 0
            state = _HOT if resident else _LOST
            if not resident and params is not None:
                if self.store.put(name, params):
                    state = _COLD
                else:
                    self.swap_drops += 1
            e = _ModelEntry(name, adapter, nbytes, pinned, state)
            self._entries[name] = e
            if resident:
                self._hbm_bytes += e.nbytes
                self._ledger_claim(e)
                if self._hbm is None:
                    # static budget: trim colder idle models to fit.  The
                    # economy has no static split to trim to — residency
                    # holds until another tenant's pressure presses it out
                    self._trim_locked()

    def pin(self, name: str, on: bool = True) -> None:
        with self._cv:
            self._entries[name].pinned = bool(on)
            self._cv.notify_all()

    # -- HBM economy (tpulab.hbm): the weights tenant ------------------------
    def _ledger_claim(self, e: "_ModelEntry") -> None:
        """Mirror a ``_hbm_bytes += e.nbytes`` into the device ledger —
        called at every site that adds hot bytes, so per-model claims sum
        exactly to this store's byte gauge (the verify() invariant)."""
        if self._hbm is not None:
            self._hbm.mirror_claim(self._hbm_tenant, e.name, e.nbytes)

    def _ledger_release(self, e: "_ModelEntry") -> None:
        if self._hbm is not None:
            self._hbm.release(self._hbm_tenant, e.name)

    def _hbm_evictable_bytes(self) -> int:
        """Non-mutating estimate for the arbiter/admission: hot bytes a
        pressure round could evict right now (unleased, unpinned, not
        busy — the same floor can_admit stands on: leased and pinned
        models are NEVER victims)."""
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if e.state == _HOT and not e.pinned and e.refs == 0
                       and not e.adapter.busy())

    def _hbm_reclaim(self, nbytes: int) -> int:
        """Arbiter pressure hook: a KV burst (or scratch discovery) needs
        device bytes — initiate write-behind swap-outs of cold unleased
        models, coldest first, until the target is covered or nothing is
        evictable.  Returns the bytes initiated (they land — and release
        their ledger claims — on the transfer collector thread)."""
        initiated = 0
        with self._cv:
            while initiated < int(nbytes):
                victim = self._evictable_locked()
                if victim is None:
                    break
                size = victim.nbytes
                if not self._swap_out_locked(victim):
                    break
                initiated += size
        return initiated

    # -- introspection -------------------------------------------------------
    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def models(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def resident_models(self) -> List[str]:
        """Names currently hot (HBM-resident), coldest first — the
        Status RPC's residency report."""
        with self._lock:
            return [n for n, e in self._entries.items() if e.state == _HOT]

    def host_models(self) -> List[str]:
        """Names whose weights sit in the host tier right now."""
        return [k for k in self.store.keys() if isinstance(k, str)]

    @property
    def hbm_bytes_in_use(self) -> int:
        """Weight bytes accounted against the HBM budget (hot models plus
        swaps in either direction that have not settled)."""
        with self._lock:
            return self._hbm_bytes

    def state_of(self, name: str) -> str:
        with self._lock:
            return self._entries[name].state

    def lease_counts(self) -> Dict[str, Dict[str, Any]]:
        """Per-model residency + lease refcounts + pins (the debugz live
        view): ``{name: {"state", "refs", "pinned", "bytes"}}``."""
        with self._lock:
            return {n: {"state": e.state, "refs": int(e.refs),
                        "pinned": bool(e.pinned), "bytes": int(e.nbytes)}
                    for n, e in self._entries.items()}

    # -- admission signal ----------------------------------------------------
    def can_admit(self, name: str) -> bool:
        """Could ``name`` be made resident without touching any leased /
        pinned / busy model?  The admission frontend queues (not rejects)
        requests while this is False — a burst on one model waits for
        another model's working set instead of thrashing it."""
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                return True  # unmanaged model: no opinion
            if e.state in (_HOT, _SWAP_IN):
                return True
            evictable = sum(
                v.nbytes for v in self._entries.values()
                if v.state == _HOT and not v.pinned and v.refs == 0
                and not v.adapter.busy())
            nbytes = e.nbytes
            if self._hbm is None:
                return (self._hbm_bytes - evictable + nbytes
                        <= self.hbm_budget_bytes)
        # arbitrated: the economy's headroom — free ledger bytes plus what
        # pressure on the OTHER tenants (demotable KV) plus own evictions
        # could free — replaces the static-budget arithmetic
        arb = self._hbm
        return (max(0, arb.free_hbm_bytes)
                + arb.reclaimable_bytes(exclude=self._hbm_tenant)
                + evictable >= nbytes)

    # -- acquire / release ---------------------------------------------------
    def acquire(self, name: str, timeout: Optional[float] = None
                ) -> ModelLease:
        """Make ``name`` resident and return a lease pinning it hot.
        Blocks (bounded) while headroom requires write-behind evictions to
        land or leased models to release; raises ``TimeoutError`` past
        ``timeout`` and ``KeyError`` for an unregistered name."""
        end = _time.monotonic() + (self.ACQUIRE_TIMEOUT_S
                                   if timeout is None else timeout)
        arbiter_denied = False
        with self._cv:
            e = self._entries[name]
            while True:
                if e.state == _HOT:
                    e.refs += 1
                    self._entries.move_to_end(name)
                    return ModelLease(self, e)
                if e.state in (_SWAP_IN, _SWAP_OUT):
                    # another acquire is promoting it / its demotion is
                    # still landing: wait for the state to settle
                    self._wait_locked(end, f"model {name!r} swap in flight")
                    continue
                # COLD or LOST: first let the economy decide (the arbiter
                # may demote idle KV for these bytes); a denial degrades
                # to the static hbm_budget_bytes path below for the rest
                # of this acquire — the pre-arbiter behavior
                if self._hbm is not None and not arbiter_denied:
                    prior = e.state
                    e.state = _SWAP_IN  # peers wait while we negotiate
                    self._cv.release()
                    try:
                        granted = self._hbm.request(
                            self._hbm_tenant, e.name, e.nbytes,
                            timeout=max(0.0, end - _time.monotonic()))
                    finally:
                        self._cv.acquire()
                    if granted:
                        self._hbm_bytes += e.nbytes
                        break
                    e.state = prior
                    arbiter_denied = True
                    self._cv.notify_all()
                    continue
                # claim the swap-in once static headroom exists
                if self._hbm_bytes + e.nbytes <= self.hbm_budget_bytes:
                    e.state = _SWAP_IN
                    self._hbm_bytes += e.nbytes
                    self._ledger_claim(e)
                    break
                # initiate evictions only beyond what in-flight swap-outs
                # will already free when they land (write-behind: the
                # accounting releases at landing, not at initiation)
                projected = self._hbm_bytes - self._pending_out_bytes
                if (projected + e.nbytes > self.hbm_budget_bytes
                        and self._evict_locked()):
                    continue
                self._wait_locked(
                    end, f"no evictable HBM headroom for {name!r} "
                    f"({self._hbm_bytes}+{e.nbytes} over "
                    f"{self.hbm_budget_bytes}B budget)")
        return self._swap_in(e)

    def _wait_locked(self, end: float, what: str) -> None:
        remaining = end - _time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"modelstore acquire timed out: {what}")
        self._cv.wait(timeout=min(0.05, remaining))

    def _release(self, e: _ModelEntry) -> None:
        with self._cv:
            if e.refs > 0:
                e.refs -= 1
            self._cv.notify_all()

    # -- swap-in (caller claimed _SWAP_IN; runs outside the lock) ------------
    def _swap_in(self, e: _ModelEntry) -> ModelLease:
        t0 = _time.perf_counter()
        host = self.store.pop(e.name)
        promoted = host is not None
        try:
            if chaos.trip("modelstore.swap") == "drop":
                raise chaos.ChaosError("injected modelstore swap drop")
        except chaos.ChaosError as ex:
            if promoted:
                # degraded swap-in: DISCARD the popped host copy and serve
                # a cold rebuild instead — stale/garbled promotion bytes
                # can never reach the device (never a corrupt serve)
                host, promoted = None, False
                self.swap_failures += 1
                log.warning("model %s swap-in degraded to cold rebuild: %s",
                            e.name, ex)
        try:
            if host is None:
                host = e.adapter.rebuild()
            e.adapter.attach(host)
        except BaseException:
            with self._cv:
                e.state = _LOST
                self._hbm_bytes -= e.nbytes
                self._ledger_release(e)
                self._cv.notify_all()
            raise
        dt = _time.perf_counter() - t0
        with self._cv:
            if promoted:
                self.swap_ins += 1
                self.swap_in_bytes += e.nbytes
            else:
                self.cold_rebuilds += 1
            e.state = _HOT
            e.refs = 1
            self._entries.move_to_end(e.name)
            self._cv.notify_all()
        if promoted and self.metrics is not None:
            self.metrics.observe_swap_in(dt, e.nbytes)
        return ModelLease(self, e)

    # -- eviction (write-behind swap-out) ------------------------------------
    def _evictable_locked(self) -> Optional[_ModelEntry]:
        for e in self._entries.values():  # OrderedDict = LRU order
            if (e.state == _HOT and not e.pinned and e.refs == 0
                    and not e.adapter.busy()):
                return e
        return None

    def _evict_locked(self) -> bool:
        victim = self._evictable_locked()
        if victim is None:
            return False
        return self._swap_out_locked(victim)

    def _trim_locked(self) -> None:
        """Kick write-behind evictions until the hot set (net of swap-outs
        already in flight) fits the budget, or nothing is evictable.
        Non-blocking: accounting converges when the copies land."""
        while (self._hbm_bytes - self._pending_out_bytes
               > self.hbm_budget_bytes):
            if not self._evict_locked():
                break

    def _swap_out_locked(self, e: _ModelEntry) -> bool:
        act = None
        try:
            if chaos.trip("modelstore.swap") == "drop":
                act = "drop"
        except chaos.ChaosError:
            act = "error"
        try:
            dev = e.adapter.detach()
        except Exception as ex:  # noqa: BLE001 - raced into busy: back off
            # a submit outside the lease contract can make the victim busy
            # between the evictability check and the detach — it simply
            # stays hot and the caller looks elsewhere / waits
            log.warning("model %s refused detach (%s); eviction backed "
                        "off", e.name, ex)
            return False
        self.evictions += 1
        if act is not None:
            # degraded swap-out: the snapshot is simply LOST — HBM frees,
            # no host copy, and the next acquire cold-rebuilds (the
            # degrade is losing work, never corrupting weights)
            e.adapter.on_detached()
            del dev
            e.state = _LOST
            self._hbm_bytes -= e.nbytes
            self._ledger_release(e)
            self.swap_failures += 1
            log.warning("model %s swap-out degraded (chaos %s): weights "
                        "dropped, next acquire cold-rebuilds", e.name, act)
            self._cv.notify_all()
            return True
        e.state = _SWAP_OUT
        self._pending_ops += 1
        self._pending_out_bytes += e.nbytes
        t0 = _time.perf_counter()
        fut = self._transfer.fetch(dev)
        fut.add_done_callback(lambda f: self._on_swapped_out(e, f, t0))
        return True

    def _on_swapped_out(self, e: _ModelEntry, fut, t0: float) -> None:
        """TransferEngine-collector-thread completion: land the host copy,
        free the device copy, release the HBM accounting, wake waiters."""
        stored = False
        try:
            host = fut.result()
            stored = self.store.put(e.name, host)
        except Exception:  # noqa: BLE001 - collector thread must live
            self.swap_failures += 1
            log.exception("model %s swap-out fetch failed; next acquire "
                          "cold-rebuilds", e.name)
        else:
            if stored:
                self.swap_outs += 1
                self.swap_out_bytes += e.nbytes
                if self.metrics is not None:
                    self.metrics.observe_swap_out(
                        _time.perf_counter() - t0, e.nbytes)
            else:
                self.swap_drops += 1
                log.warning(
                    "model %s swap-out dropped: host tier refused %d bytes "
                    "(budget %d) — host budget undersized?", e.name,
                    e.nbytes, self.store.budget_bytes)
        finally:
            try:
                e.adapter.on_detached()
            except Exception:  # noqa: BLE001 - accounting must still settle
                log.exception("model %s on_detached failed", e.name)
            with self._cv:
                e.state = _COLD if stored else _LOST
                self._hbm_bytes -= e.nbytes
                self._ledger_release(e)
                self._pending_out_bytes -= e.nbytes
                self._pending_ops -= 1
                self._cv.notify_all()

    # -- lifecycle -----------------------------------------------------------
    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every write-behind swap-out has settled (tests,
        shutdown).  False on timeout."""
        with self._cv:
            return self._cv.wait_for(lambda: self._pending_ops == 0,
                                     timeout)

    def close(self) -> None:
        self.drain(timeout=2.0)
        if self._owns_transfer:
            self._transfer.shutdown()
        self.store.clear()


# -- the bench row ------------------------------------------------------------
def benchmark_multi_model(switches: int = 6, steps: int = 8,
                          prompt_len: int = 8, vocab: int = 128,
                          d_model: int = 64, n_layers: int = 2,
                          n_heads: int = 4) -> Dict[str, Any]:
    """The bench ``multi_model`` row: an interleaved two-model trace
    (a transformer LLM through the paged batcher + a dense ViT-style
    classifier) under HBM weight pressure — the budget holds ONE model's
    weights, so every switch is a swap.

    Multiplexer **on**: switches ride host-tier swap-ins (promote the
    bytes that left the device).  **Off** (the pre-modelstore baseline):
    every switch is a serial cold rebuild — re-init + re-place.  Both
    modes must produce identical outputs (``parity``/``llm_parity``);
    the headline is mean swap-in vs cold-build latency and the eviction
    count."""
    import jax
    import jax.numpy as jnp

    from tpulab.engine.paged import ContinuousBatcher
    from tpulab.models.transformer import init_transformer_params
    from tpulab.models.vit import init_vit_params, vit_apply

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, (prompt_len,), np.int32)
    image = rng.standard_normal((1, 32, 32, 3)).astype(np.float32)

    def build_llm_params():
        return init_transformer_params(vocab=vocab, d_model=d_model,
                                       n_heads=n_heads, n_layers=n_layers,
                                       d_ff=4 * d_model, seed=0)

    def build_vit_params():
        return init_vit_params(variant="s", image_size=32, patch_size=16,
                               num_classes=10, seed=0)

    vit_fn = jax.jit(lambda p, x: vit_apply(
        p, {"input": x}, n_heads=6, n_layers=12, patch_size=16,
        compute_dtype=jnp.float32)["logits"])

    class _VitServable:
        """Minimal dense-model adapter target for the bench (the real
        path uses CompiledModelAdapter; the swap mechanics are shared)."""

        def __init__(self):
            self.device_params = jax.device_put(build_vit_params())

        def resident(self):
            return self.device_params is not None

        def param_bytes(self):
            return tree_nbytes(self.device_params or build_vit_params())

        def busy(self):
            return False

        def detach(self):
            dev, self.device_params = self.device_params, None
            return dev

        def on_detached(self):
            pass

        def attach(self, host_tree):
            self.device_params = jax.device_put(host_tree)

        def rebuild(self):
            return build_vit_params()

    def run(mux_on: bool) -> Dict[str, Any]:
        cb = ContinuousBatcher(build_llm_params(), n_heads=n_heads,
                               n_layers=n_layers, lanes=2,
                               max_len=prompt_len + steps + 4,
                               compute_dtype=jnp.float32)
        vit = _VitServable()
        llm_bytes = tree_nbytes(cb.params)
        vit_bytes = vit.param_bytes()
        # holds the bigger model (plus half the smaller) but never both:
        # every switch in the trace is forced to swap
        budget = (max(llm_bytes, vit_bytes)
                  + min(llm_bytes, vit_bytes) // 2)
        mux = None
        if mux_on:
            mux = WeightMultiplexer(budget)
            mux.register("llm", BatcherAdapter(cb, build_llm_params))
            mux.register("vit", _VitServableAdapter(vit))
        tokens: List[List[int]] = []
        logits: List[np.ndarray] = []
        swap_in_s: List[float] = []
        cold_s: List[float] = []
        t_all = _time.perf_counter()
        try:
            for i in range(switches):
                want_llm = i % 2 == 0
                name = "llm" if want_llm else "vit"
                t0 = _time.perf_counter()
                if mux is not None:
                    was_cold = mux.state_of(name) != _HOT
                    rebuilds0 = mux.cold_rebuilds
                    lease = mux.acquire(name)
                    mux.drain()
                    if was_cold:
                        (cold_s if mux.cold_rebuilds > rebuilds0
                         else swap_in_s).append(
                            _time.perf_counter() - t0)
                else:
                    # serial-rebuild baseline: the OTHER model's weights
                    # are dropped and this one is rebuilt from scratch
                    if want_llm and cb.params is None:
                        cb.params = jax.device_put(build_llm_params(),
                                                   cb.pool.device)
                        cold_s.append(_time.perf_counter() - t0)
                    elif not want_llm and vit.device_params is None:
                        vit.attach(build_vit_params())
                        cold_s.append(_time.perf_counter() - t0)
                    lease = None
                try:
                    if want_llm:
                        fut = cb.submit(prompt, steps)
                        tokens.append([int(t) for t in
                                       fut.result(timeout=300)])
                    else:
                        logits.append(np.asarray(vit_fn(vit.device_params,
                                                        image)))
                finally:
                    if lease is not None:
                        lease.release()
                if mux is None:  # baseline drops the model it just used
                    if want_llm:
                        cb.params = None
                    else:
                        vit.device_params = None
            wall = _time.perf_counter() - t_all
            out = {
                "wall_s": round(wall, 3),
                "llm_tokens": tokens,
                "vit_logits_digest": [round(float(np.abs(l).sum()), 4)
                                      for l in logits],
                "cold_build_ms_mean": round(
                    1e3 * float(np.mean(cold_s)), 2) if cold_s else None,
                "swap_in_ms_mean": round(
                    1e3 * float(np.mean(swap_in_s)), 2) if swap_in_s
                else None,
            }
            if mux is not None:
                out.update(evictions=mux.evictions, swap_ins=mux.swap_ins,
                           swap_outs=mux.swap_outs,
                           cold_rebuilds=mux.cold_rebuilds,
                           hbm_budget_mb=round(budget / 2**20, 2))
            return out
        finally:
            cb.shutdown()
            if mux is not None:
                mux.close()

    on, off = run(True), run(False)
    llm_parity = on.pop("llm_tokens") == off.pop("llm_tokens")
    vit_parity = (on.pop("vit_logits_digest")
                  == off.pop("vit_logits_digest"))
    son, soff = on.get("swap_in_ms_mean"), off.get("cold_build_ms_mean")
    return {
        "switches": switches, "steps": steps,
        "mux_on": on, "mux_off": off,
        "llm_parity": llm_parity, "vit_parity": vit_parity,
        "parity": llm_parity and vit_parity,
        "swap_in_faster_than_cold_build": (
            son is not None and soff is not None and son < soff),
    }


class _VitServableAdapter:
    """Adapter façade over the bench's ``_VitServable`` (same protocol as
    CompiledModelAdapter/BatcherAdapter)."""

    def __init__(self, servable):
        self._s = servable

    def resident(self):
        return self._s.resident()

    def param_bytes(self):
        return self._s.param_bytes()

    def busy(self):
        return self._s.busy()

    def detach(self):
        return self._s.detach()

    def on_detached(self):
        self._s.on_detached()

    def attach(self, host_tree):
        self._s.attach(host_tree)

    def rebuild(self):
        return self._s.rebuild()
