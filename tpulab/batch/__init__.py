"""Offline batch lane: preemptible bulk inference that soaks idle
capacity (docs/SERVING.md "Offline batch lane").

Fleets are paid for 24/7 but online traffic is diurnal — the
adaptive-orchestration line in PAPERS.md frames cost/performance/
resilience as one scheduling problem, and this package is the repo's
answer: a second request class (bulk scoring, evals, distillation
traces) that runs ONLY from spare capacity and is always the first
preemption victim.  The expensive primitives already exist elsewhere —
tiered-KV preempt/resume (tpulab.kvcache) makes eviction nearly free,
delivered-token resume (the ``resume_length`` discipline,
docs/ROBUSTNESS.md "Stream failover semantics") restarts a killed job
without re-decoding, and the HBM arbiter (tpulab.hbm) knows the real
headroom — so the lane is composition:

- :class:`BatchJob` — the manifest: prompts + sampling config + steps.
- :class:`JSONLResultSink` — the durable result/checkpoint file: tokens
  append as they are delivered, so a killed job resumes from delivered
  tokens instead of restarting.
- :class:`BatchScheduler` — feeds job items into a
  :class:`~tpulab.engine.paged.ContinuousBatcher` only while spare
  capacity exists (idle lanes + free KV pages + arbiter headroom above
  a floor — the same unified headroom admission uses), tagged
  ``request_class="batch"`` so the engine preempts them first and the
  admission frontend keeps them strictly below any online priority.
"""

from tpulab.batch.bench import benchmark_batch_soak  # noqa: F401
from tpulab.batch.job import BatchJob, JSONLResultSink  # noqa: F401
from tpulab.batch.scheduler import BatchScheduler  # noqa: F401

__all__ = ["BatchJob", "JSONLResultSink", "BatchScheduler",
           "benchmark_batch_soak"]
