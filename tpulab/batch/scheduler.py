"""BatchScheduler: feed offline jobs into spare serving capacity
(tpulab.batch, docs/SERVING.md "Offline batch lane").

The scheduler is deliberately a FEEDER, not a second engine: it walks a
:class:`~tpulab.batch.job.BatchJob`'s items and submits each into the
:class:`~tpulab.engine.paged.ContinuousBatcher` with
``request_class="batch"`` — the engine then owns preemption (batch
lanes are the first victims of any online arrival) and the admission
frontend, when armed, keeps batch strictly below every online priority.
The feeder's own job is the SPARE-CAPACITY gate: an item is submitted
only while

- the engine has an idle lane and an empty queue (batch must never
  delay an online admit inside the engine),
- the unified headroom covers the item's cost — via
  :meth:`~tpulab.serving.admission.AdmissionController.headroom_ok`
  when an admission controller is attached (the SAME number online
  admission uses: free pages + demotable KV + arbiter reclaimable),
  else the pool's free pages directly,
- with an HBM arbiter armed, ``free_hbm_bytes`` sits at or above
  ``min_free_hbm_bytes``.

Progress checkpoints to the :class:`~tpulab.batch.job.JSONLResultSink`
as tokens are delivered, so a preempted/killed run RESUMES: an item
with N delivered tokens resubmits ``prompt + delivered`` and decodes
only the remaining ``steps - N`` — one chunked prefill, zero re-decode
of delivered tokens, bit-exact for greedy and device-sampled jobs (the
``resume_length`` discipline of docs/ROBUSTNESS.md applied to the
offline lane).  Host-sampled items restart from scratch (draw-order
PRNG does not survive) behind an explicit ``reset`` checkpoint record.

The ``batch.run`` chaos trip point (tpulab.chaos) sits at the feed
site: ``error`` kills the run mid-feed (in-flight items are cancelled,
their delivered tokens stay durable), ``drop`` black-holes the feeder
the same way with distinct evidence — both model a batch runner dying,
and the next :meth:`BatchScheduler.run` resumes from the checkpoint.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

import numpy as np

from tpulab import chaos
from tpulab.batch.job import BatchJob, ItemProgress, JSONLResultSink

log = logging.getLogger("tpulab.batch")


class BatchScheduler:
    """Feed batch jobs into a ContinuousBatcher's spare capacity.

    ``engine`` is the batcher; ``sink`` the durable result/checkpoint
    file (None = results kept in memory only, no resume across runs);
    ``admission`` an optional
    :class:`~tpulab.serving.admission.AdmissionController` — armed, each
    item holds a batch-class admission ticket while in flight and the
    spare probe consults the controller's unified headroom;
    ``metrics`` an optional
    :class:`~tpulab.utils.metrics.BatchMetrics`.  ``max_inflight``
    bounds concurrently submitted items (default 1: the lane soaks idle
    capacity one lane at a time and yields instantly under preemption).
    """

    def __init__(self, engine, sink: Optional[JSONLResultSink] = None,
                 admission=None, tenant: str = "batch",
                 max_inflight: int = 1, min_free_hbm_bytes: int = 0,
                 poll_s: float = 0.002, metrics=None):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.engine = engine
        self.sink = sink
        self.admission = admission
        self.tenant = tenant
        self.max_inflight = int(max_inflight)
        self.min_free_hbm_bytes = int(min_free_hbm_bytes)
        self.poll_s = float(poll_s)
        self.metrics = metrics
        self._paused = threading.Event()
        #: live in-flight map future -> (item, ticket); guarded by _lock
        self._inflight: Dict[object, tuple] = {}
        self._lock = threading.Lock()
        # -- counters (test-assertable; BatchMetrics.poll mirrors them) -----
        self.jobs_run = 0
        self.jobs_running = 0
        self.jobs_done = 0
        self.items_done = 0
        self.tokens_delivered = 0
        #: delivered tokens a resume did NOT re-decode (the replay-
        #: avoided figure: prompt+delivered rides one chunked prefill)
        self.tokens_resume_skipped = 0
        #: delivered tokens a non-resumable (host-sampled) restart threw
        #: away — the price of draw-order PRNG, kept visible
        self.tokens_restart_lost = 0
        self.interrupted_runs = 0
        self.spare_denials = 0  # feed attempts deferred by the gate

    # -- drain hook (fleet scale-down: batch drains FIRST) -------------------
    def pause(self) -> None:
        """Stop feeding new items (in-flight items finish or are
        preempted/cancelled by their owner); :meth:`resume_feeding`
        re-arms."""
        self._paused.set()

    def resume_feeding(self) -> None:
        self._paused.clear()

    @property
    def paused(self) -> bool:
        return self._paused.is_set()

    def drain(self, address: Optional[str] = None) -> None:
        """Fleet scale-down hook (docs/SERVING.md "Fleet routing &
        autoscaling"): batch work drains FIRST — stop feeding and cancel
        every in-flight item NOW (delivered tokens are already durable
        in the sink; the next run resumes them), so the replica's drain
        only waits on online streams.  ``address`` is accepted for the
        autoscaler's ``batch_drain(victim)`` calling convention and
        ignored here: an in-process scheduler feeds one engine."""
        self.pause()
        with self._lock:
            futs = list(self._inflight)
        for fut in futs:
            try:
                self.engine.cancel(fut)
            except Exception:  # pragma: no cover - engine torn down
                log.exception("batch drain: cancel failed")
        if self.sink is not None:
            self.sink.flush()

    # -- the spare-capacity gate ---------------------------------------------
    def spare_capacity(self, cost: int) -> bool:
        """May one more batch item enter the engine RIGHT NOW?  (module
        docstring) — idle lane + empty engine queue + unified headroom +
        arbiter floor.  Never raises: a torn-down engine reads False."""
        eng = self.engine
        try:
            lanes = int(getattr(eng, "lanes", 0) or 0)
            if lanes and int(eng.active_lanes) >= lanes:
                return False
            if int(getattr(eng, "queued_requests", 0)) > 0:
                return False
            if self.admission is not None:
                if not self.admission.headroom_ok(cost):
                    return False
            else:
                pool = getattr(eng, "pool", None)
                if pool is not None:
                    page_size = int(getattr(eng, "page_size", 1) or 1)
                    if int(pool.free_pages) * page_size < cost:
                        return False
            hbm = getattr(eng, "hbm", None)
            if hbm is not None and self.min_free_hbm_bytes > 0:
                if int(hbm.free_hbm_bytes) < self.min_free_hbm_bytes:
                    return False
        except Exception:  # noqa: BLE001 - a dying engine is not spare
            return False
        return True

    # -- the run loop ---------------------------------------------------------
    def run(self, job: BatchJob, timeout_s: Optional[float] = None) -> dict:
        """Run (or RESUME) ``job`` to completion from spare capacity.

        Returns a report dict: ``items_done``/``items_total``,
        ``tokens_delivered`` (this run), ``tokens_resume_skipped``
        (delivered tokens this run did not re-decode),
        ``batch_preemptions`` (engine evictions of this run's lanes),
        ``interrupted`` (None, or the chaos action that killed the
        feeder — the next ``run`` resumes from the checkpoint), and
        ``results``: item -> token list for every item COMPLETED as of
        this run.  Idempotent: items already done in the sink are
        skipped, partial items resume from their delivered prefix."""
        t0 = time.perf_counter()
        self.jobs_run += 1
        self.jobs_running += 1
        try:
            return self._run(job, t0, timeout_s)
        finally:
            self.jobs_running -= 1

    def _run(self, job: BatchJob, t0: float,
             timeout_s: Optional[float]) -> dict:
        progress = (self.sink.load_progress(job.job_id)
                    if self.sink is not None else {})
        results: Dict[int, list] = {}
        pending = []
        for i in range(len(job)):
            p = progress.get(i)
            if p is not None and p.done:
                results[i] = list(p.tokens)
                continue
            # a partial whose delivered prefix already ends the item
            # (stop token, or the full step budget) just needs its done
            # record — nothing left to decode
            if p is not None and p.tokens and (
                    len(p.tokens) >= job.steps
                    or p.tokens[-1] in job.stop_tokens):
                results[i] = list(p.tokens)
                self.items_done += 1
                self._finish_item(job, i, p.tokens)
                continue
            pending.append(i)
        preempt0 = int(getattr(self.engine, "batch_preemptions", 0))
        tokens0 = self.tokens_delivered
        skipped0 = self.tokens_resume_skipped
        interrupted: Optional[str] = None
        end = None if timeout_s is None else time.monotonic() + timeout_s
        pending.reverse()  # pop() from the front, cheaply
        while pending or self._inflight:
            # chaos: the batch runner's fault site — tripped once per
            # scheduler pass, so a rule can kill the run at ANY point
            # (feeding or waiting on in-flight decodes).  error/drop
            # both kill the runner mid-job: in-flight work is cancelled,
            # delivered tokens stay durable in the sink, and the next
            # run() resumes from the checkpoint with zero re-decode
            try:
                if chaos.trip("batch.run") == "drop":
                    interrupted = "drop"
                    self._cancel_inflight()
                    break
            except chaos.ChaosError:
                interrupted = "error"
                self._cancel_inflight()
                break
            if end is not None and time.monotonic() > end:
                interrupted = "timeout"
                self._cancel_inflight()
                break
            if not pending or self.paused:
                # in-flight items complete via their done-callbacks;
                # nothing to feed — just wait for slots/completions
                time.sleep(self.poll_s)
                continue
            with self._lock:
                slots = self.max_inflight - len(self._inflight)
            if slots <= 0:
                time.sleep(self.poll_s)
                continue
            item = pending[-1]
            delivered = list(progress.get(item, ItemProgress()).tokens)
            cost = int(len(job.prompts[item]) + job.steps)
            if not self.spare_capacity(cost):
                self.spare_denials += 1
                time.sleep(self.poll_s)
                continue
            pending.pop()
            try:
                self._submit_item(job, item, delivered, results)
            except Exception:  # noqa: BLE001 - keep the job going
                log.exception("batch item %d submit failed; re-queued",
                              item)
                pending.insert(0, item)
                time.sleep(self.poll_s)
        if interrupted is not None:
            self.interrupted_runs += 1
        if self.sink is not None:
            self.sink.flush()  # interruption or completion: land deltas
        done = len(results)
        if done == len(job) and interrupted is None:
            self.jobs_done += 1
        report = {
            "job_id": job.job_id, "items_total": len(job),
            "items_done": done,
            "tokens_delivered": self.tokens_delivered - tokens0,
            "tokens_resume_skipped":
                self.tokens_resume_skipped - skipped0,
            "batch_preemptions":
                int(getattr(self.engine, "batch_preemptions", 0))
                - preempt0,
            "interrupted": interrupted,
            "wall_s": round(time.perf_counter() - t0, 6),
            "results": results,
        }
        return report

    # -- internals ------------------------------------------------------------
    def _cancel_inflight(self) -> None:
        with self._lock:
            futs = list(self._inflight)
        for fut in futs:
            try:
                self.engine.cancel(fut)
            except Exception:  # pragma: no cover
                log.exception("batch cancel failed")
        # settle: cancelled lanes free at the next tick boundary; the
        # report must not race its own done-callbacks
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._lock:
                if not self._inflight:
                    return
            time.sleep(self.poll_s)

    def _admit_ticket(self, job: BatchJob, cost: int):
        if self.admission is None:
            return None
        from tpulab.serving.admission import REQUEST_CLASS_BATCH
        return self.admission.admit(tenant=self.tenant, cost=cost,
                                    priority=job.priority,
                                    request_class=REQUEST_CLASS_BATCH)

    def _submit_item(self, job: BatchJob, item: int, delivered: list,
                     results: Dict[int, list]) -> None:
        prompt = job.prompts[item]
        start = 0
        if delivered:
            if job.resumable:
                # delivered-token resume: the prompt already contains the
                # delivered prefix, so it rides ONE chunked prefill and
                # only the remaining steps decode — zero re-decode,
                # bit-exact ((seed, position)-keyed streams)
                start = len(delivered)
                prompt = np.concatenate(
                    [prompt, np.asarray(delivered, np.int32)])
                self.tokens_resume_skipped += start
            else:
                # host-sampled: draw-order PRNG does not survive the
                # restart — void the prefix behind an explicit checkpoint
                # record and start the item over
                self.tokens_restart_lost += len(delivered)
                if self.sink is not None:
                    self.sink.mark_reset(job.job_id, item)
                delivered = []
        steps = job.steps - start
        collected: list = list(delivered)
        sink = self.sink

        def on_token(tok, i, logprob=None):
            collected.append(int(tok))
            self.tokens_delivered += 1
            if sink is not None:
                sink.append_token(job.job_id, item, start + i, int(tok))

        cost = int(len(prompt) + steps)
        ticket = self._admit_ticket(job, cost)
        try:
            fut = self.engine.submit(
                prompt, steps, on_token=on_token,
                sampling=job.sampling(), priority=job.priority,
                stop_tokens=job.stop_tokens, tenant=self.tenant,
                request_class="batch")
        except Exception:
            if ticket is not None:
                ticket.release()
            raise
        with self._lock:
            self._inflight[fut] = (item, ticket)

        def _done(f):
            with self._lock:
                entry = self._inflight.pop(f, None)
            if entry is None:  # pragma: no cover - double callback
                return
            _item, tk = entry
            if tk is not None:
                tk.release()
            err = None
            try:
                if not f.cancelled():
                    err = f.exception()
            except Exception as e:  # pragma: no cover
                err = e
            if f.cancelled() or err is not None:
                # preempted runs resume in-engine; only a CANCELLED or
                # failed item lands here — its delivered tokens are
                # already durable, the next run() resumes them
                if err is not None:
                    log.warning("batch item %d failed: %r", _item, err)
                return
            self.items_done += 1
            results[_item] = list(collected)
            self._finish_item(job, _item, collected)

        fut.add_done_callback(_done)

    def _finish_item(self, job: BatchJob, item: int,
                     tokens: list) -> None:
        if self.sink is not None:
            self.sink.mark_done(job.job_id, item, len(tokens))

    @property
    def soak_utilization(self) -> float:
        """Fraction of engine lanes the batch lane occupies RIGHT NOW
        (the utilization-soak gauge BatchMetrics exports): near 1 on an
        idle fleet, near 0 under online load — both are the lane
        working as designed."""
        lanes = int(getattr(self.engine, "lanes", 0) or 0)
        if lanes <= 0:
            return 0.0
        with self._lock:
            return min(1.0, len(self._inflight) / lanes)
