"""Batch-job manifest + durable JSONL result sink (tpulab.batch).

A :class:`BatchJob` is the unit of offline work: a list of prompts that
share one sampling config and step budget (bulk scoring, evals,
distillation traces).  Results land in a :class:`JSONLResultSink` — an
append-only JSONL file that doubles as the job's CHECKPOINT: token
deltas append as they are delivered (write-behind, bounded flush), so a
preempted or killed run resumes from the delivered prefix via the
delivered-token resume discipline (docs/ROBUSTNESS.md "Stream failover
semantics") instead of re-decoding — zero re-decode of delivered
tokens, bit-exact for greedy and device-sampled jobs.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np


class BatchJob:
    """One offline job: ``prompts`` (each a sequence of token ids) that
    share ``steps`` and one sampling config.  ``resumable`` jobs (greedy
    or device-sampled — (seed, position)-keyed streams) continue
    bit-exact from delivered tokens after a kill; host-sampled jobs
    ("host sampling allowed": the lane never streams to a human) restart
    interrupted items from scratch — their PRNG is keyed by draw order,
    which does not survive the restart."""

    def __init__(self, job_id: str, prompts: Sequence[Sequence[int]],
                 steps: int, temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: Optional[int] = None,
                 device_sampling: bool = False,
                 stop_tokens: Sequence[int] = (), priority: int = 0,
                 metadata: Optional[dict] = None):
        if not job_id:
            raise ValueError("job_id must be non-empty")
        if not prompts:
            raise ValueError("a batch job needs at least one prompt")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        self.job_id = str(job_id)
        self.prompts: List[np.ndarray] = [
            np.asarray(p, np.int32).reshape(-1) for p in prompts]
        for i, p in enumerate(self.prompts):
            if p.size == 0:
                raise ValueError(f"prompt {i} is empty")
        self.steps = int(steps)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        self.device_sampling = bool(device_sampling)
        self.stop_tokens = tuple(int(t) for t in stop_tokens)
        #: priority WITHIN the batch class (the engine ranks every online
        #: request above every batch request regardless of this)
        self.priority = int(priority)
        self.metadata = dict(metadata or {})

    def __len__(self) -> int:
        return len(self.prompts)

    @property
    def resumable(self) -> bool:
        """Delivered-token resume is bit-exact only for (seed,
        position)-keyed streams: greedy, or device sampling."""
        return self.temperature <= 0.0 or self.device_sampling

    def sampling(self):
        """The job's :class:`~tpulab.engine.paged.SamplingParams`
        (None = greedy, the engine default)."""
        if self.temperature <= 0.0:
            return None
        from tpulab.engine.paged import SamplingParams
        return SamplingParams(temperature=self.temperature,
                              top_k=self.top_k, top_p=self.top_p,
                              seed=self.seed, device=self.device_sampling)

    # -- manifest (JSON) roundtrip ------------------------------------------
    def to_manifest(self) -> dict:
        return {"job_id": self.job_id,
                "prompts": [[int(t) for t in p] for p in self.prompts],
                "steps": self.steps, "temperature": self.temperature,
                "top_k": self.top_k, "top_p": self.top_p,
                "seed": self.seed,
                "device_sampling": self.device_sampling,
                "stop_tokens": list(self.stop_tokens),
                "priority": self.priority, "metadata": self.metadata}

    @classmethod
    def from_manifest(cls, doc: dict) -> "BatchJob":
        return cls(doc["job_id"], doc["prompts"], doc["steps"],
                   temperature=doc.get("temperature", 0.0),
                   top_k=doc.get("top_k", 0), top_p=doc.get("top_p", 0.0),
                   seed=doc.get("seed"),
                   device_sampling=doc.get("device_sampling", False),
                   stop_tokens=doc.get("stop_tokens", ()),
                   priority=doc.get("priority", 0),
                   metadata=doc.get("metadata"))


class ItemProgress:
    """One job item's recovered state (JSONLResultSink.load_progress)."""

    __slots__ = ("tokens", "done")

    def __init__(self, tokens: Optional[List[int]] = None,
                 done: bool = False):
        self.tokens: List[int] = list(tokens or [])
        self.done = bool(done)


class JSONLResultSink:
    """Append-only JSONL result file that doubles as the job checkpoint.

    Record shapes (one JSON object per line):

    - ``{"job": id, "item": i, "start": N, "tokens": [...]}`` — a token
      DELTA: positions ``N .. N+len-1`` of item ``i``'s generation.
      Deltas append in order; ``start`` makes replayed/overlapping
      flushes idempotent at load.
    - ``{"job": id, "item": i, "done": true, "n": total}`` — the item
      completed with ``total`` tokens.
    - ``{"job": id, "item": i, "reset": true}`` — delivered tokens are
      void (a host-sampled item restarting from scratch: its PRNG draw
      order does not survive); the loader discards everything earlier.

    Appends buffer per item and flush every ``flush_every`` tokens (and
    at done/reset/close), bounding the write amplification of
    token-granular checkpointing; ``flush()`` fsyncs when ``fsync=True``
    (off by default — tests and bench run on tmpfs-class paths).
    Thread-safe: token callbacks arrive on the engine's scheduler
    thread while the batch scheduler marks items done from callbacks.
    """

    def __init__(self, path: str, flush_every: int = 16,
                 fsync: bool = False):
        self.path = str(path)
        self.flush_every = max(1, int(flush_every))
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        #: (job, item) -> [start, [buffered tokens]]
        self._buf: Dict[tuple, list] = {}
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    # -- writes -------------------------------------------------------------
    def _write_locked(self, rec: dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())

    def _flush_item_locked(self, key: tuple) -> None:
        entry = self._buf.pop(key, None)
        if not entry or not entry[1]:
            return
        job, item = key
        self._write_locked({"job": job, "item": item, "start": entry[0],
                            "tokens": entry[1]})

    def append_token(self, job_id: str, item: int, index: int,
                     token: int) -> None:
        """One delivered token at absolute position ``index`` of the
        item's generation (checkpoint-as-you-go)."""
        key = (job_id, int(item))
        with self._lock:
            entry = self._buf.get(key)
            if entry is not None and entry[0] + len(entry[1]) != int(index):
                # non-contiguous (an interrupted item restarting): flush
                # what we hold and start a fresh delta at the new start
                self._flush_item_locked(key)
                entry = None
            if entry is None:
                entry = self._buf[key] = [int(index), []]
            entry[1].append(int(token))
            if len(entry[1]) >= self.flush_every:
                self._flush_item_locked(key)

    def mark_done(self, job_id: str, item: int, n_tokens: int) -> None:
        key = (job_id, int(item))
        with self._lock:
            self._flush_item_locked(key)
            self._write_locked({"job": job_id, "item": int(item),
                                "done": True, "n": int(n_tokens)})

    def mark_reset(self, job_id: str, item: int) -> None:
        """Void an item's delivered tokens (host-sampled restart)."""
        key = (job_id, int(item))
        with self._lock:
            self._buf.pop(key, None)
            self._write_locked({"job": job_id, "item": int(item),
                                "reset": True})

    def flush(self) -> None:
        """Land every buffered delta (run interruption, shutdown)."""
        with self._lock:
            for key in list(self._buf):
                self._flush_item_locked(key)

    # -- recovery -----------------------------------------------------------
    def load_progress(self, job_id: str) -> Dict[int, ItemProgress]:
        """Recover per-item state from the file: delivered tokens (in
        order, duplicates from overlapping flushes dropped via
        ``start``) and done flags.  Unparseable trailing garbage (a
        torn final write from a kill) is skipped — everything durable
        before it survives."""
        out: Dict[int, ItemProgress] = {}
        if not os.path.exists(self.path):
            return out
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn write: keep what landed before it
                if rec.get("job") != job_id:
                    continue
                item = int(rec.get("item", -1))
                if item < 0:
                    continue
                p = out.setdefault(item, ItemProgress())
                if rec.get("reset"):
                    p.tokens = []
                    p.done = False
                elif rec.get("done"):
                    p.done = True
                elif "tokens" in rec:
                    start = int(rec.get("start", len(p.tokens)))
                    toks = [int(t) for t in rec["tokens"]]
                    if start > len(p.tokens):
                        continue  # gap (lost delta): keep the prefix only
                    # overlap from a replayed flush: drop the duplicate
                    # prefix, append the genuinely new suffix
                    p.tokens.extend(toks[len(p.tokens) - start:])
        return out
