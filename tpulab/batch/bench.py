"""bench.py ``batch_soak`` row: chip-utilization lift from the offline
batch lane under a diurnal online trace, lane ON vs OFF.

One continuous batcher serves a seeded diurnal online trace — bursts of
concurrent streaming requests separated by idle valleys (the shape a
fleet paid for 24/7 actually sees).  Lane OFF is today's behavior: the
valleys are wasted capacity.  Lane ON runs a
:class:`~tpulab.batch.BatchScheduler` soaking the valleys with a bulk
job; every burst preempts the batch lane (it is the first victim by
construction) and the valley resumes it.

The claims tracked: total tokens/s strictly higher with the lane ON
(the soak), online p99 TTFT/ITL flat within noise under the SAME online
trace (preemptible work must not tax the interactive path), batch
preemptions > 0 (the bursts really did evict the lane), and the
preempted job's output bit-exact vs an uncontended run of the same job
(in-engine preempt/resume is exact — tiered-KV swap or re-prefill).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List


def benchmark_batch_soak(lanes: int = 2, steps: int = 12,
                         n_cycles: int = 4, idle_s: float = 0.3,
                         n_batch_items: int = 24, prompt_len: int = 8,
                         seed: int = 0) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from tpulab.batch import BatchJob, BatchScheduler
    from tpulab.engine.paged import ContinuousBatcher
    from tpulab.models.transformer import init_transformer_params

    params = init_transformer_params(vocab=128, d_model=32, n_heads=2,
                                     n_layers=2, d_ff=64)
    rng = np.random.default_rng(seed)
    online_prompts = [rng.integers(0, 128, (prompt_len,), np.int32)
                      for _ in range(n_cycles * lanes)]
    batch_prompts = [rng.integers(0, 128, (prompt_len,), np.int32)
                     for _ in range(n_batch_items)]
    job_kw = dict(steps=steps, temperature=0.7, device_sampling=True,
                  seed=1234)

    def make_engine() -> ContinuousBatcher:
        return ContinuousBatcher(
            params, n_heads=2, n_layers=2, lanes=lanes,
            max_len=max(64, prompt_len + steps + 16), page_size=8,
            decode_block=8, compute_dtype=jnp.float32)

    def warm(cb: ContinuousBatcher) -> None:
        # cover every compiled path the trace exercises so the measured
        # window pays routing + scheduling, not jit.  Phases on purpose:
        # streaming-only lanes compile the K<=2 scan (with a queue
        # pressure present the adaptive K would stay high and skip it),
        # a lone batch-style submit compiles the K=8 block and its K=4
        # trailing block, both sharing the pow2 prefill bucket.
        futs = [cb.submit(online_prompts[0], steps,
                          on_token=lambda *a: None) for _ in range(lanes)]
        for f in futs:
            f.result(timeout=600)
        cb.submit(batch_prompts[0], steps,
                  request_class="batch").result(timeout=600)

    def online_trace(cb: ContinuousBatcher) -> dict:
        """The diurnal trace: n_cycles bursts of ``lanes`` concurrent
        streaming requests, each followed by an idle valley."""
        ttfts: List[float] = []
        itls: List[float] = []
        tokens = [0]
        lock = threading.Lock()
        first_tokens: Dict[int, int] = {}

        def one(idx: int) -> None:
            t0 = time.perf_counter()
            last = [t0]
            got = []

            def on_token(tok, i):
                now = time.perf_counter()
                with lock:
                    if not got:
                        ttfts.append(now - t0)
                    else:
                        itls.append(now - last[0])
                    tokens[0] += 1
                got.append(int(tok))
                last[0] = now

            cb.submit(online_prompts[idx], steps,
                      on_token=on_token).result(timeout=600)
            with lock:
                first_tokens[idx] = got[0]

        t_run = time.perf_counter()
        for c in range(n_cycles):
            threads = [threading.Thread(
                target=one, args=(c * lanes + k,), daemon=True)
                for k in range(lanes)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            time.sleep(idle_s)  # the valley the lane exists to soak
        wall = time.perf_counter() - t_run
        arr = np.asarray(sorted(ttfts))
        iarr = np.asarray(sorted(itls))

        def q(a, p):
            return round(float(np.quantile(a, p)) * 1e3, 2) if a.size \
                else 0.0
        return {"wall_s": round(wall, 3), "online_tokens": tokens[0],
                "ttft_ms_p50": q(arr, 0.5), "ttft_ms_p99": q(arr, 0.99),
                "itl_ms_p50": q(iarr, 0.5), "itl_ms_p99": q(iarr, 0.99),
                "first_tokens": dict(first_tokens)}

    out = {"lanes": lanes, "steps": steps, "n_cycles": n_cycles,
           "idle_s": idle_s, "n_batch_items": n_batch_items}

    # -- lane OFF: the online trace alone (valleys wasted) -------------------
    cb = make_engine()
    try:
        warm(cb)
        off = online_trace(cb)
        off["total_tokens_s"] = round(off["online_tokens"]
                                      / off["wall_s"], 1)
    finally:
        cb.shutdown()

    # -- uncontended batch reference (parity target) -------------------------
    cb = make_engine()
    try:
        warm(cb)
        sched = BatchScheduler(cb)
        ref = sched.run(BatchJob("soak-ref", batch_prompts, **job_kw),
                        timeout_s=600)
        ref_results = ref["results"]
    finally:
        cb.shutdown()

    # -- lane ON: same online trace + the soak -------------------------------
    cb = make_engine()
    try:
        warm(cb)
        sched = BatchScheduler(cb)
        report = {}

        def soak() -> None:
            report.update(sched.run(
                BatchJob("soak", batch_prompts, **job_kw), timeout_s=600))

        worker = threading.Thread(target=soak, daemon=True)
        worker.start()
        on = online_trace(cb)
        batch_tokens_in_window = sched.tokens_delivered
        worker.join(timeout=600)  # the job drains in the trailing idle
        on["batch_tokens_in_window"] = int(batch_tokens_in_window)
        on["total_tokens_s"] = round(
            (on["online_tokens"] + batch_tokens_in_window)
            / on["wall_s"], 1)
        out["batch_preemptions"] = report.get("batch_preemptions", 0)
        out["batch_items_done"] = report.get("items_done", 0)
        # a preempted job's output is bit-exact vs the uncontended run
        out["batch_parity"] = (
            report.get("interrupted") is None
            and {k: v for k, v in report.get("results", {}).items()}
            == ref_results)
    finally:
        cb.shutdown()

    # the online stream itself is unchanged by the lane (greedy picks)
    out["online_parity"] = off["first_tokens"] == on["first_tokens"]
    off.pop("first_tokens")
    on.pop("first_tokens")
    out["lane_off"] = off
    out["lane_on"] = on
    out["tokens_s_lift"] = round(
        on["total_tokens_s"] / max(1e-9, off["total_tokens_s"]), 3)
    return out
