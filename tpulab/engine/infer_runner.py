"""InferRunner: the three-stage async pipeline (reference infer_runner.h:37-157,
call stack SURVEY §3.2).

Stage map (reference -> here):
- caller/pre: get_buffers [MAY BLOCK] -> create bindings -> fill host inputs
- "dispatch" worker (reference "cuda" thread): async H2D, two-level context
  acquisition [MAY BLOCK], async program dispatch, async D2H record — the
  dispatch thread only *launches* async work, so one thread keeps many
  contexts busy (reference hot-loop note §3.2)
- "post" worker: blocks on device completion, returns the context token,
  lands outputs in staging, runs the user's post_fn, returns buffers,
  fulfills the future
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

import numpy as np

from tpulab.core.async_compute import SharedPackagedTask
from tpulab.engine.buffers import Bindings


class InferRunner:
    """Future-returning inference pipeline bound to one model
    (reference InferRunner)."""

    def __init__(self, manager, model_name: str):
        self._mgr = manager
        self.model = manager.model(model_name)
        self.model_name = model_name

    # -- public API ---------------------------------------------------------
    def infer(self, post_fn: Optional[Callable[[Bindings], Any]] = None,
              **arrays: np.ndarray) -> Future:
        """Run inference on named input arrays; returns a future of
        ``post_fn(bindings)`` (default: dict of output arrays)."""
        if not arrays:
            raise ValueError("no input arrays given")
        batch = next(iter(arrays.values())).shape[0]
        buffers_item = self._mgr.get_buffers()           # MAY BLOCK (backpressure)
        try:
            bindings = buffers_item.get().create_bindings(self.model, batch)
            for name, arr in arrays.items():
                bindings.set_input(name, np.ascontiguousarray(arr))
        except BaseException:
            buffers_item.release()                       # never leak the slot
            raise
        return self.infer_bindings(bindings, buffers_item, post_fn)

    def infer_bindings(self, bindings: Bindings, buffers_item,
                       post_fn: Optional[Callable[[Bindings], Any]] = None) -> Future:
        """Pipeline entry for pre-filled bindings (reference Infer(bindings))."""
        post_fn = post_fn or (lambda b: {k: v.copy() for k, v in b.outputs().items()})
        task: SharedPackagedTask = SharedPackagedTask(post_fn)
        future = task.get_future()
        self._mgr.workers("dispatch").enqueue(
            self._dispatch_stage, bindings, buffers_item, task)
        return future

    # -- stages -------------------------------------------------------------
    def _dispatch_stage(self, bindings: Bindings, buffers_item,
                        task: SharedPackagedTask) -> None:
        managed = None
        try:
            bindings.copy_to_device()                    # async H2D
            managed = self._mgr.get_execution_context(   # MAY BLOCK (2-level pop)
                self.model_name)
            ctx = managed.get()
            outputs = ctx.infer(bindings.device_inputs, bindings.bucket)  # async
            bindings.copy_from_device(outputs)           # record async D2H source
            poller = self._mgr.event_poller
            engine = self._mgr.transfer_engine
            if poller is not None and engine is not None:
                # execution token returns the moment *compute* is done
                # (reference post stage ctx sync-then-reset, infer_runner.h:93);
                # D2H rides the coalescing TransferEngine and the post stage
                # chains on its future — post threads never block on fetches.
                import time as _time
                t_dispatch = _time.monotonic()

                def _compute_done(b=bindings, m=managed, t0=t_dispatch):
                    # device-side compute duration, measured at the compute
                    # site (metrics: the reference's per-stage cudaEvent
                    # timing analog)
                    b.compute_seconds = _time.monotonic() - t0
                    m.release()

                poller.watch(outputs, _compute_done)
                fetch = engine.fetch(outputs)
                fetch.add_done_callback(
                    lambda f: self._mgr.workers("post").enqueue(
                        self._post_stage_fetched, bindings, buffers_item,
                        task, f))
            else:
                self._mgr.workers("post").enqueue(
                    self._post_stage, bindings, buffers_item, managed, task)
        except BaseException as e:  # noqa: BLE001
            if managed is not None:
                managed.release()                        # token must not strand
            buffers_item.release()
            if not task.get_future().done():
                task.get_future().set_exception(e)

    def _post_stage_fetched(self, bindings: Bindings, buffers_item,
                            task: SharedPackagedTask, fetch_fut) -> None:
        try:
            host = fetch_fut.result()
            # hand the fetched private arrays to outputs() directly — no
            # staging round trip; the default post_fn then pays ONE copy
            # (slice-to-batch) instead of copy-in + copy-out
            bindings.fetched_outputs = host
            # per-request compute-site timing for metrics consumers (read
            # after .result(); avoids the shared-attr race)
            task.get_future()._tpulab_compute_s = getattr(
                bindings, "compute_seconds", None)
            task(bindings)                               # user post fn -> future
        except BaseException as e:  # noqa: BLE001
            if not task.get_future().done():
                task.get_future().set_exception(e)
        finally:
            bindings.release()
            buffers_item.release()

    def _post_stage(self, bindings: Bindings, buffers_item, managed,
                    task: SharedPackagedTask) -> None:
        try:
            bindings.synchronize()                       # block on compute+D2H
            managed.release()                            # token back first
            task(bindings)                               # user post fn -> future
        except BaseException as e:  # noqa: BLE001
            if not task.get_future().done():
                task.get_future().set_exception(e)
        finally:
            managed.release()                            # idempotent safety net
            bindings.release()
            buffers_item.release()                       # buffers back to pool
