"""Generation engine: session-pooled autoregressive serving.

Beyond the reference's scope (trtlab predates LLM serving) but squarely in
this framework's long-context mandate: KV caches are the activation-scratch
of generative serving, so they get the same treatment the reference gives
execution contexts — preallocated, pooled, leased per request with blocking
backpressure (SURVEY §2.5 token-pool semantics).

- :class:`GenerationEngine` — owns device params, the jitted decode step and
  batch ``generate`` program, and a pool of cache slots.
- :class:`GenerationSession` — one leased cache slot: ``prefill(tokens)``
  then ``step()`` per token (streaming), or ``generate(prompt, n)`` one-shot.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any, Dict, Iterator, Optional

import numpy as np

from tpulab import chaos
from tpulab.core.deadline import Deadline
from tpulab.core.pool import Pool, PoolItem


class GenerationEngine:
    """Pooled generation over tpulab's transformer family."""

    def __init__(self, params: Any, n_heads: int, n_layers: int,
                 max_len: int = 1024, max_sessions: int = 2,
                 compute_dtype=None, device=None,
                 n_kv_heads: Optional[int] = None,
                 rope_theta: Optional[float] = None):
        import jax
        import jax.numpy as jnp
        from tpulab.models.transformer import (init_kv_cache,
                                               make_generate_fn,
                                               transformer_decode_step)
        from tpulab.tpu import platform as plat

        self.device = device if device is not None else plat.local_device(0)
        compute_dtype = compute_dtype or jnp.bfloat16
        self.compute_dtype = compute_dtype
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.n_kv_heads = n_kv_heads or n_heads
        self.max_len = max_len
        self.params = jax.device_put(params, self.device)
        from tpulab.models.transformer import weight_shape
        d_model = weight_shape(params["layer0"]["wqkv"])[0]
        self.head_dim = d_model // n_heads
        #: id-validation bound (public: the Generate RPC checks it)
        self.vocab = int(weight_shape(params["embed"])[0])

        self._decode = jax.jit(partial(
            transformer_decode_step, n_heads=n_heads, n_layers=n_layers,
            compute_dtype=compute_dtype, n_kv_heads=self.n_kv_heads,
            rope_theta=rope_theta))
        self._generate = make_generate_fn(self.params, n_heads, n_layers,
                                          max_len, compute_dtype,
                                          n_kv_heads=self.n_kv_heads,
                                          rope_theta=rope_theta)
        # cache slots hold the compact n_kv_heads form under GQA: the
        # generation analog of execution-context pooling
        self._init_cache = partial(init_kv_cache, 1, max_len, n_layers,
                                   self.n_kv_heads, self.head_dim,
                                   compute_dtype)
        self._sessions: Pool = Pool(
            (self._init_cache() for _ in range(max_sessions)))

    def _check_ids(self, tokens: np.ndarray) -> None:
        """Host-boundary id validation: XLA gather CLAMPS out-of-bounds
        ids (silent garbage tokens) — reject here instead, mirroring
        ContinuousBatcher.submit so direct library callers are covered,
        not just the Generate RPC (ADVICE r5)."""
        if tokens.size and (tokens.min() < 0 or tokens.max() >= self.vocab):
            raise ValueError(f"prompt token ids outside [0, {self.vocab})")

    # -- one-shot -----------------------------------------------------------
    def generate(self, prompt: np.ndarray, steps: int) -> np.ndarray:
        """Batch greedy generation (jitted prefill+decode scan)."""
        import jax.numpy as jnp
        self._check_ids(np.asarray(prompt))
        return np.asarray(self._generate(jnp.asarray(prompt), steps))

    # -- streaming sessions --------------------------------------------------
    def start_session(self, timeout: Optional[float] = None) -> "GenerationSession":
        """Lease a cache slot; blocks when all sessions are busy.  The
        blocking wait is recorded on the session (``lease_wait_s``) — the
        dense engine's queue-wait, observable by serving telemetry."""
        import time as _time
        t0 = _time.perf_counter()
        item = self._sessions.pop(timeout)
        return GenerationSession(self, item,
                                 lease_wait_s=_time.perf_counter() - t0)

    @property
    def available_sessions(self) -> int:
        return self._sessions.available


class GenerationSession:
    """One leased KV-cache slot (close/GC returns it to the pool)."""

    def __init__(self, engine: GenerationEngine, item: PoolItem,
                 lease_wait_s: float = 0.0):
        self._engine = engine
        self._item = item
        self._cache = item.get()
        self._pos = 0
        self._last_logits = None
        self._closed = False
        #: seconds this lease blocked on the session pool (queue wait)
        self.lease_wait_s = lease_wait_s

    @property
    def position(self) -> int:
        return self._pos

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("generation session is closed")

    def prefill(self, tokens: np.ndarray) -> None:
        """Feed prompt tokens ((T,) int32) through decode steps."""
        import jax.numpy as jnp
        self._check_open()
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        self._engine._check_ids(tokens)
        if self._pos + len(tokens) > self._engine.max_len:
            raise ValueError(f"session length {self._pos + len(tokens)} "
                             f"exceeds max_len {self._engine.max_len}")
        for t in tokens:
            self._last_logits, self._cache = self._engine._decode(
                self._engine.params, self._cache,
                jnp.asarray([t], jnp.int32), jnp.int32(self._pos))
            self._pos += 1

    def step(self, token: Optional[int] = None) -> int:
        """Advance one token; ``token=None`` feeds back the greedy argmax
        of the last logits (generation), else feeds the given token
        (teacher-forced scoring).  Returns the next predicted token."""
        import jax.numpy as jnp
        self._check_open()
        if self._last_logits is None and token is None:
            raise RuntimeError("prefill before generating")
        if token is None:
            token = int(np.asarray(self._last_logits).argmax(-1)[0])
        elif not 0 <= int(token) < self._engine.vocab:
            raise ValueError(f"token id {token} outside "
                             f"[0, {self._engine.vocab})")
        if self._pos >= self._engine.max_len:
            raise ValueError(f"session exceeded max_len {self._engine.max_len}")
        # chaos: per-decode-step fault site (transient failure / slow step)
        chaos.trip("engine.step")
        self._last_logits, self._cache = self._engine._decode(
            self._engine.params, self._cache,
            jnp.asarray([token], jnp.int32), jnp.int32(self._pos))
        self._pos += 1
        return int(np.asarray(self._last_logits).argmax(-1)[0])

    def stream(self, steps: int,
               deadline: Optional[Deadline] = None) -> Iterator[int]:
        """Yield ``steps`` greedily generated tokens.  An expired
        ``deadline`` raises DeadlineExceeded BEFORE the next decode step
        (library-caller analog of the Generate RPC's per-token check)."""
        tok = None
        for _ in range(steps):
            if deadline is not None:
                deadline.check("generation")
            tok = self.step(tok)
            yield tok

    def close(self) -> None:
        """Return the cache slot.  Decode is functional (each step yields a
        fresh cache tree), so the pooled slot keeps its pristine zero cache
        and the next lease starts clean; the session's working caches are
        garbage once released.  (Buffer donation per step is the next
        optimization — it requires copy-on-lease so the pooled buffers are
        never donated away.)"""
        if not self._closed:
            self._closed = True
            self._cache = None
            self._item.release()

    def __enter__(self) -> "GenerationSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
