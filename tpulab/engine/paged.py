"""Paged KV cache + continuous batching.

The dense :mod:`generation` engine leases one max_len cache per session; this
module is the scalable successor (the TPU literature's ragged/paged-attention
serving shape): K/V live in a global pool of fixed-size *pages*, sessions own
*block tables* of page ids, and a scheduler steps every active session in one
fused batched decode per tick — continuous batching: new requests join the
batch the moment a slot frees, finished ones leave without draining the rest.

TPU-first mechanics:
- the page pools are donated through the jitted step, so XLA updates K/V
  in place (no per-token pool copies);
- the step has a *static* shape (fixed lane count B, fixed max pages per
  sequence) — one compiled program regardless of which sessions occupy the
  lanes; inactive lanes are masked, not recompiled;
- attention either gathers pages via the block table (pool[tables] ->
  (B, MP*S, ...), the XLA fallback) or walks them in the pallas ragged
  paged-attention kernel family (tpulab.ops.ragged_attention: per-lane
  (query_len, kv_len) segments serve decode, K+1 verify, and mixed
  chunked-prefill+decode rounds in one program, KV-heads-sharded under
  a mesh — docs/PERFORMANCE.md "Ragged paged attention");
- decode runs K ticks per dispatch (:func:`paged_decode_block`: lax.scan over
  the step, on-device sampling + stop masks), so the host pays one dispatch
  and ONE blocking fetch per K tokens — off-chip the per-token cost is the
  host<->device RTT, and K amortizes it (docs/PERFORMANCE.md).
"""

from __future__ import annotations

import functools
import threading
import time as _time
from concurrent.futures import Future
from functools import partial
from typing import Any, Dict, List, Optional

import numpy as np

from tpulab import chaos
from tpulab.core.deadline import Deadline, DeadlineExceeded


class PagedKVPool:
    """Global paged K/V storage + free-page accounting (host side)."""

    def __init__(self, n_pages: int, page_size: int, n_layers: int,
                 n_heads: int, head_dim: int, dtype=None, device=None,
                 allocator=None, mesh=None):
        import jax.numpy as jnp
        from tpulab.tpu import platform as plat
        from tpulab.tpu.allocators import make_tpu_allocator

        dtype = dtype or jnp.bfloat16
        self.n_pages = n_pages
        self.page_size = page_size
        self.n_layers = n_layers
        # sharded serving: with a ``mesh`` the page *payloads* shard over
        # the ``model`` axis on the KV-heads dim (each shard holds its own
        # heads' K/V, matching the column-parallel wqkv that writes them)
        # while the page *tables* — host-side int32 id maps — stay
        # replicated: one logical page id still names one logical page.
        self.mesh = mesh
        self.kv_sharding = None
        if mesh is not None:
            from tpulab.parallel.sharding import kv_pool_sharding
            n_model = dict(mesh.shape).get("model", 0)
            if not n_model:
                raise ValueError("pool mesh needs a 'model' axis")
            if n_heads % n_model:
                raise ValueError(
                    f"pool KV heads ({n_heads}) not divisible by the mesh "
                    f"model axis ({n_model}) — page payloads shard on the "
                    "KV-heads dim")
            self.kv_sharding = kv_pool_sharding(mesh)
            self.device = (device if device is not None
                           else mesh.devices.flat[0])
        else:
            self.device = (device if device is not None
                           else plat.local_device(0))
        # FUSED page layout: a page's K rows ([..., 0, :, :, :]) and V rows
        # ([..., 1, :, :, :]) are adjacent in HBM, so the pallas decode
        # kernel fetches both with ONE DMA per page (the walk is
        # DMA-issue-bound; fusing halves the issue count)
        self._shape = (n_layers, n_pages, 2, page_size, n_heads, head_dim)
        self._dtype = dtype
        # the KV page store is an HBM block owned by the device allocator
        # framework (tracked bytes; reference cuda_allocators device memory);
        # each donated decode step rotates the buffer via replace().  Under
        # a mesh the allocator binds the NamedSharding (device_put accepts
        # it) and its byte accounting stays LOGICAL — per-shard HBM is
        # hbm_bytes_per_shard.
        self._alloc = allocator or make_tpu_allocator(self.placement)
        self._kv_addr, self._kv = self._alloc.allocate_array(self._shape,
                                                             dtype)
        # page 0 is RESERVED as scratch: inactive/padded lanes scatter their
        # (masked-out) K/V there, so it must never hold live data
        self._free: List[int] = list(range(1, n_pages))
        self._refs: Dict[int, int] = {}  # live page -> refcount
        self._lock = threading.Lock()
        #: allocate lowest page ids first (the HBM arbiter arms this):
        #: live data packs toward page 0, so the TOP of the store stays
        #: contiguously free and :meth:`shrink` can return real bytes
        self.prefer_low_pages = False

    # the KV buffer rotates through XLA donation; the setter keeps the
    # device allocator's accounting slot pointing at the live generation
    @property
    def kv(self):
        return self._kv

    @kv.setter
    def kv(self, value) -> None:
        self._kv = self._alloc.replace(self._kv_addr, value)

    @property
    def dtype(self):
        """Page storage dtype (may be narrower than the compute dtype —
        KV-cache quantization)."""
        return self._dtype

    @property
    def placement(self):
        """``device_put`` target for pool-shaped (and page-payload-shaped)
        arrays: the NamedSharding under a mesh, the bound device
        otherwise."""
        return self.kv_sharding if self.kv_sharding is not None \
            else self.device

    @property
    def n_shards(self) -> int:
        """Model-axis shard count of the page payloads (1 single-device)."""
        return int(self.mesh.shape["model"]) if self.mesh is not None else 1

    @property
    def hbm_bytes(self) -> int:
        """Live LOGICAL HBM of this pool's page store (not allocator-wide:
        the allocator may be shared, e.g. a Runtime's).  Under a mesh this
        is the whole-array figure; each shard holds hbm_bytes_per_shard."""
        return (self._alloc.node_size(self._kv_addr)
                if self._kv_addr is not None else 0)

    @property
    def hbm_bytes_per_shard(self) -> int:
        """Per-device HBM of the page store — the figure that must fit one
        chip (admission headroom counts logical pages; a logical page
        costs 1/n_shards of its bytes on each shard)."""
        return self.hbm_bytes // self.n_shards

    def reset(self) -> None:
        """Re-materialize the pool (recovery after a failed donated step)."""
        import jax
        import jax.numpy as jnp
        self.kv = jax.device_put(jnp.zeros(self._shape, self._dtype),
                                 self.placement)
        with self._lock:
            self._free = list(range(1, self.n_pages))  # page 0 stays scratch
            self._refs.clear()

    def close(self) -> None:
        """Eagerly free the page store's HBM."""
        if self._kv_addr is not None:
            self._alloc.deallocate_node(self._kv_addr)
            self._kv_addr = None
            self._kv = None

    @property
    def page_nbytes(self) -> int:
        """Tracked HBM bytes one logical page costs (every layer's K+V
        rows for its slots) — the ledger/admission conversion factor."""
        return self.hbm_bytes // max(1, self.n_pages)

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def allocate_page(self) -> Optional[int]:
        with self._lock:
            if not self._free:
                return None
            if self.prefer_low_pages:
                page = min(self._free)
                self._free.remove(page)
            else:
                page = self._free.pop()
            self._refs[page] = 1
            return page

    def add_ref(self, page: int) -> None:
        """Share an allocated page (prefix caching): one extra
        release_pages() is now required before the page frees."""
        with self._lock:
            if page not in self._refs:
                raise ValueError(f"add_ref on non-live page {page}")
            self._refs[page] += 1

    def release_pages(self, pages: List[int]) -> None:
        """Drop one reference per page; pages free when the count hits 0
        (pages from pre-refcount callers behave exactly as before: one
        allocate, one release)."""
        with self._lock:
            for p in pages:
                if not p:
                    continue  # 0/None never re-enter
                n = self._refs.get(p, 1) - 1
                if n <= 0:
                    self._refs.pop(p, None)
                    self._free.append(p)
                else:
                    self._refs[p] = n

    def refcount(self, page: int) -> int:
        """Current reference count (0 for free/unknown pages)."""
        with self._lock:
            return self._refs.get(page, 0)

    # -- elastic capacity (the HBM economy, tpulab.hbm) ----------------------
    # The page store is no longer a fixed pre-carve: under an arbiter the
    # batcher grows it when a KV burst wins bytes from the other tenants
    # and shrinks it when a model's residency squeezes KV back.  Both ops
    # re-materialize the store through the tracked allocator's replace()
    # slot, so the framework HBM gauge (and the ledger claim mirroring
    # it) follows the real byte count exactly.  Page ids are STABLE:
    # grow appends ids, shrink only drops contiguously free ids off the
    # top — no live block table ever needs remapping.
    def shrinkable_pages(self) -> int:
        """Free pages contiguously at the TOP of the store — the ids a
        shrink could drop right now without touching live data."""
        with self._lock:
            free = set(self._free)
            n = 0
            p = self.n_pages - 1
            while p >= 1 and p in free:
                n += 1
                p -= 1
            return n

    def grow(self, extra_pages: int) -> int:
        """Append ``extra_pages`` zeroed pages to the store (one device
        concat through the allocator's accounting slot).  Returns the
        pages added.  Scheduler-thread only, like every other mutation of
        the live ``kv`` buffer."""
        extra = int(extra_pages)
        if extra <= 0:
            return 0
        import jax
        import jax.numpy as jnp
        pad_shape = (self._shape[0], extra) + self._shape[2:]
        pad = jax.device_put(jnp.zeros(pad_shape, self._dtype),
                             self.placement)
        self.kv = jnp.concatenate([self._kv, pad], axis=1)
        with self._lock:
            self._free.extend(range(self.n_pages, self.n_pages + extra))
            self.n_pages += extra
            self._shape = (self._shape[0], self.n_pages) + self._shape[2:]
        return extra

    def shrink(self, drop_pages: int) -> int:
        """Drop up to ``drop_pages`` contiguously free pages off the TOP
        of the store (one device slice through the accounting slot).
        Returns the pages actually dropped — capped by what is free at
        the top; never page 0, never a live id."""
        with self._lock:
            free = set(self._free)
            k = 0
            p = self.n_pages - 1
            while p >= 1 and p in free and k < int(drop_pages):
                k += 1
                p -= 1
            if k == 0:
                return 0
            cut = self.n_pages - k
            self._free = [q for q in self._free if q < cut]
            self.n_pages = cut
            self._shape = (self._shape[0], cut) + self._shape[2:]
        self.kv = self._kv[:, :cut]
        return k


@functools.lru_cache(maxsize=None)
def _kernel_compiles(n_heads: int, head_dim: int, page_size: int,
                     compute_dtype, device,
                     n_kv_heads: Optional[int] = None,
                     kv_dtype=None) -> bool:
    """One-shot probe: does the pallas ragged kernel compile+run on this
    device for this head geometry?  Cached per geometry; a Mosaic
    rejection (tiling/VMEM limits, unsupported pool dtype) selects the
    XLA gather fallback.  Under a mesh the caller passes the PER-SHARD
    head counts — one shard's compile is the whole family's proxy."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tpulab.ops.ragged_attention import ragged_paged_attention
    try:
        q = jax.device_put(
            jnp.zeros((1, 1, n_heads, head_dim), compute_dtype), device)
        kvp = jax.device_put(
            jnp.zeros((2, 2, page_size, n_kv_heads or n_heads, head_dim),
                      kv_dtype or compute_dtype),
            device)
        out = ragged_paged_attention(
            q, kvp, np.zeros((1, 2), np.int32),
            np.ones((1,), np.int32), np.ones((1,), np.int32),
            interpret=False)
        jax.block_until_ready(out)
        return True
    except Exception as e:
        import logging
        logging.getLogger("tpulab.engine").warning(
            "pallas paged-attention kernel unavailable on this device "
            "(%s: %s); using the XLA gather fallback",
            type(e).__name__, str(e)[:200])
        return False


@functools.lru_cache(maxsize=None)
def _flash_compiles(head_dim: int, compute_dtype, device) -> bool:
    """One-shot probe: does the pallas flash-attention kernel compile+run
    on this device at this head_dim?  Mosaic rejection selects the dense
    causal fallback for prefill."""
    import jax
    import jax.numpy as jnp
    from tpulab.ops.flash_attention import flash_attention
    try:
        q = jax.device_put(jnp.zeros((1, 128, 1, head_dim), compute_dtype),
                           device)
        out = flash_attention(q, q, q, causal=True, interpret=False)
        jax.block_until_ready(out)
        return True
    except Exception as e:
        import logging
        logging.getLogger("tpulab.engine").warning(
            "pallas flash-attention prefill unavailable on this device "
            "(%s: %s); using dense causal attention",
            type(e).__name__, str(e)[:200])
        return False


def _gather_attend(q, k_layer, v_layer, tables, qpos, compute_dtype):
    """Dense-gather paged attention (the XLA fallback math, single source
    of truth for decode ticks and extend/chunked prefill).

    q (B, M, H, D) query tokens; k_layer/v_layer (P, S, Hkv, D) one
    layer's pools; tables (B, MP) page ids; qpos (B, M) global position
    of each query token (visibility: context j attends iff j <= qpos).
    Returns (B, M, H*D).
    """
    import jax
    import jax.numpy as jnp
    from tpulab.models.transformer import repeat_kv

    b, m, h, d = q.shape
    mp = tables.shape[1]
    page_size = k_layer.shape[1]
    k_ctx = repeat_kv(k_layer[tables].reshape(b, mp * page_size, -1, d), h)
    v_ctx = repeat_kv(v_layer[tables].reshape(b, mp * page_size, -1, d), h)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k_ctx.astype(jnp.float32)) / np.sqrt(d)
    j = jnp.arange(mp * page_size)
    mask = j[None, None, :] <= qpos[:, :, None]          # (B, M, K)
    scores = jnp.where(mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(compute_dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v_ctx.astype(compute_dtype)).reshape(b, m, h * d)


def paged_decode_step(params, kv_pool, tables, lengths, tokens,
                      active, n_heads: int, n_layers: int,
                      compute_dtype, use_kernel: bool = False,
                      n_kv_heads: Optional[int] = None,
                      rope_theta: Optional[float] = None,
                      temps=None, seeds=None,
                      kernel_geometry: Optional[tuple] = None,
                      mesh=None):
    """One batched decode tick over the paged pool.

    Shapes: kv_pool (L, P, 2, S, Hkv, D) fused page store (axis 2 = K/V),
    tables (B, MP) int32 page ids (padded rows repeat page 0),
    lengths (B,) current position per lane, tokens (B,), active (B,) bool.
    Returns (logits (B, vocab), kv_pool) — the pool donated by the caller.
    Under GQA (``n_kv_heads < n_heads``) the pool holds ``n_kv_heads``
    heads per slot.

    With ``temps (B,) f32`` + ``seeds (B, 2) uint32`` the return becomes
    (next_tokens (B,) i32, logprobs (B,) f32, logits, kv_pool): lanes
    with temp > 0 are Gumbel-max temperature-sampled ON DEVICE with a key
    folded from (seed, position) — batch-composition- and
    preemption-invariant — and temp == 0 lanes take the argmax;
    ``logprobs`` is each lane's chosen-token log-probability
    (log-softmax at the chosen id).  Callers then fetch only (B,)-sized
    arrays (no per-tick (B, vocab) logits transfer).
    """
    import jax.numpy as jnp
    from tpulab.models.transformer import (_dense_ffn, _lm_head, _rmsnorm,
                                           apply_rope, qmat, split_qkv)

    n_kv = n_kv_heads or n_heads
    b = tokens.shape[0]
    page_size = kv_pool.shape[3]
    emb = params["embed"].astype(compute_dtype)
    x = emb[tokens][:, None, :]
    d_model = x.shape[-1]
    head_dim = d_model // n_heads
    # write target per lane: page id + slot for position `lengths`
    page_idx = tables[jnp.arange(b), lengths // page_size]      # (B,)
    slot_idx = lengths % page_size                              # (B,)

    for layer in range(n_layers):
        p = params[f"layer{layer}"]
        h = _rmsnorm(x, p["ln1"]["scale"])
        qkv = h @ qmat(p["wqkv"], compute_dtype)
        q, knew, vnew = split_qkv(qkv, b, 1, n_heads, n_kv, head_dim)
        if rope_theta:
            # per-lane positions: each lane decodes at its own length
            q = apply_rope(q, lengths[:, None], rope_theta)
            knew = apply_rope(knew, lengths[:, None], rope_theta)
        knew = knew[:, 0].astype(kv_pool.dtype)      # (B, Hkv, D)
        vnew = vnew[:, 0].astype(kv_pool.dtype)
        # scatter the new K/V into their pages; inactive/padded lanes are
        # routed to the RESERVED scratch page 0 so they can never clobber
        # a live lane's pages
        safe_page = jnp.where(active, page_idx, 0)
        safe_slot = jnp.where(active, slot_idx, 0)
        kv_pool = kv_pool.at[layer, safe_page, 0, safe_slot].set(knew)
        kv_pool = kv_pool.at[layer, safe_page, 1, safe_slot].set(vnew)
        if use_kernel:
            # pallas ragged kernel at the q=1 decode shape: walks block
            # tables page-by-page, no dense gather materialization; fused
            # pages = 1 DMA/page; under a mesh the walk shards on the
            # KV-heads dim via shard_map (tpulab.ops.ragged_attention)
            from tpulab.ops.ragged_attention import ragged_paged_attention
            gk, nk = kernel_geometry or (None, None)
            attn = ragged_paged_attention(
                q, kv_pool[layer], tables,
                jnp.ones_like(lengths), lengths + 1,
                mesh=mesh, g_pages=gk, nbuf=nk,
            ).astype(compute_dtype).reshape(b, 1, d_model)
        else:
            # XLA fallback: gather pages densely then mask
            attn = _gather_attend(q, kv_pool[layer, :, 0],
                                  kv_pool[layer, :, 1], tables,
                                  lengths[:, None], compute_dtype)
        x = x + attn @ qmat(p["wo"], compute_dtype)
        h2 = _rmsnorm(x, p["ln2"]["scale"])
        x = x + _dense_ffn(p, h2, compute_dtype).astype(x.dtype)

    x = _rmsnorm(x, params["final_norm"]["scale"])
    logits = _lm_head(params, x[:, 0])
    # inactive lanes emit neutral logits (argmax 0) — callers mask on active
    logits = jnp.where(active[:, None], logits, 0.0)
    if temps is None:
        return logits, kv_pool
    import jax
    next_tokens = jax.vmap(_device_sample_token)(
        logits, temps, seeds.astype(jnp.uint32), lengths)
    logp_rows = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    logprobs = jnp.take_along_axis(logp_rows, next_tokens[:, None],
                                   axis=-1)[:, 0]
    return next_tokens, logprobs, logits, kv_pool


def paged_decode_step_sampled(params, kv_pool, tables, lengths, tokens,
                              active, temps, seeds, **kw):
    """Positional-signature variant of :func:`paged_decode_step` with
    device sampling armed — sharded jits need every array argument
    positional so explicit ``in_shardings`` can be attached."""
    return paged_decode_step(params, kv_pool, tables, lengths, tokens,
                             active, temps=temps, seeds=seeds, **kw)


def paged_decode_block(params, kv_pool, tables, lengths, tokens, active,
                       temps, seeds, steps_rem, stop_ids,
                       n_heads: int, n_layers: int, compute_dtype,
                       k: int = 8, use_kernel: bool = False,
                       n_kv_heads: Optional[int] = None,
                       rope_theta: Optional[float] = None,
                       kernel_geometry: Optional[tuple] = None,
                       mesh=None):
    """K fused decode ticks in ONE dispatch: ``lax.scan`` over
    :func:`paged_decode_step`, sampling every step on device.

    The per-token serving cost off-chip is dominated by the host<->device
    round trip (dispatch + blocking fetch), not the decode math — chaining
    K steps inside one compiled program amortizes that RTT over K tokens
    (the host then syncs once per K tokens instead of once per token, the
    fused multi-token decode shape of TPU-native serving stacks).

    Per-lane device-side stop mask: a lane is *live* while it is active,
    has steps remaining, and has not emitted a stop token.  ``steps_rem
    (B,) i32`` counts tokens still wanted per lane; ``stop_ids (B, S)
    i32`` holds each lane's stop-token ids padded with -1 (token ids are
    always >= 0, so the pad never matches).  A stop token IS emitted as
    the lane's final token (matching the host-side contract), then the
    lane goes dead for the rest of the block: its K/V writes route to the
    reserved scratch page and its position stops advancing — which also
    keeps the (seed, position)-folded device-sampling stream identical to
    a K=1 run.

    The CALLER pre-allocates pages: step j writes K/V at ``lengths + j``
    for live lanes, so ``tables`` must already cover every position the
    block can reach.

    Returns ``(tokens (B, K) i32, logprobs (B, K) f32, emitted (B, K)
    bool, lengths (B,), last_tokens (B,), live (B,), steps_rem (B,),
    kv_pool)`` — the trailing five are the carried state *after* the
    block, returned as device arrays so a follow-up block can be
    dispatched without a host round trip (dispatch-ahead overlap).
    ``emitted[b]`` is a prefix mask: lane b's valid tokens are
    ``tokens[b, :emitted[b].sum()]``.
    """
    import jax
    import jax.numpy as jnp

    def body(carry, _):
        kv, lens, toks, live, rem = carry
        nt, lp, _logits, kv = paged_decode_step(
            params, kv, tables, lens, toks, live,
            n_heads=n_heads, n_layers=n_layers,
            compute_dtype=compute_dtype, use_kernel=use_kernel,
            n_kv_heads=n_kv_heads, rope_theta=rope_theta,
            temps=temps, seeds=seeds, kernel_geometry=kernel_geometry,
            mesh=mesh)
        emitted = live
        nt = jnp.where(live, nt, toks)           # dead lanes hold position
        lens = lens + emitted.astype(jnp.int32)
        rem = rem - emitted.astype(jnp.int32)
        hit_stop = (nt[:, None] == stop_ids).any(axis=1)
        live = live & (rem > 0) & ~hit_stop
        return (kv, lens, nt, live, rem), (nt, lp, emitted)

    init = (kv_pool, lengths, tokens, active, steps_rem)
    (kv_pool, lengths, tokens, live, steps_rem), (toks, lps, ems) = \
        jax.lax.scan(body, init, None, length=k)
    return (toks.T, lps.T, ems.T, lengths, tokens, live, steps_rem,
            kv_pool)


def _device_sample_token(row, temp, seed2, pos):
    """Gumbel-max temperature sample of one lane: key folded from the full
    64-bit seed (lo, hi words) and the token position — the SINGLE
    definition of the device-sampling stream (the decode step vmaps it;
    the prefill first-token pick replays it on the fetched logits row so
    one request is one stream end to end)."""
    import jax
    import jax.numpy as jnp
    key = jax.random.fold_in(
        jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), seed2[0]), seed2[1]),
        pos)
    g = jax.random.gumbel(key, row.shape, jnp.float32)
    safe_t = jnp.where(temp > 0, temp, 1.0)
    sampled = jnp.argmax(row / safe_t + g)
    return jnp.where(temp > 0, sampled, jnp.argmax(row)).astype(jnp.int32)


def paged_ragged_forward(params, kv_pool, tables, seq, q_lens, kv_lens,
                         n_heads: int, n_layers: int, compute_dtype,
                         use_kernel: bool = False,
                         n_kv_heads: Optional[int] = None,
                         rope_theta: Optional[float] = None,
                         mesh=None,
                         kernel_geometry: Optional[tuple] = None,
                         last_only: bool = False):
    """One fused multi-token forward over ragged per-lane segments — the
    single program shape behind the ragged dispatch plan (ROADMAP item
    2, "Ragged Paged Attention" in PAPERS.md).

    ``seq (B, M)`` int32, left-packed: lane b's valid tokens are
    ``seq[b, :q_lens[b]]``, token j at global position
    ``kv_lens[b] - q_lens[b] + j``.  Per layer all valid positions' K/V
    scatter into the lane's pages first (invalid positions route to the
    reserved scratch page 0), then attention gathers the lane's whole
    block table masked by global causality — the gather-after-scatter
    shape of :func:`paged_extend`, batched over ragged lanes.  One
    static ``M`` serves every segment mix: plain decode (``q_lens=1``),
    K+1 speculative verify (``q_lens=k+1``), chunked prefill
    (``q_lens=chunk``) and any combination in one batch.

    ``use_kernel`` selects the pallas ragged kernel
    (:func:`tpulab.ops.ragged_attention.ragged_paged_attention`; under a
    ``mesh`` it shards on the KV-heads dim via shard_map) over the XLA
    dense-gather fallback.  ``last_only=True`` runs the vocab head over
    each lane's LAST valid position only and returns ``(logits (B,
    vocab), kv_pool)``; otherwise ``(logits (B, M, vocab), kv_pool)``
    with invalid positions' logits garbage the caller must not consume.
    The fused pool is donated by the caller either way.
    """
    import jax.numpy as jnp
    from tpulab.models.transformer import (_dense_ffn, _lm_head, _rmsnorm,
                                           apply_rope, qmat, split_qkv)

    n_kv = n_kv_heads or n_heads
    b, m = seq.shape
    page_size = kv_pool.shape[3]
    emb = params["embed"].astype(compute_dtype)
    x = emb[seq]                                      # (B, M, D)
    d_model = x.shape[-1]
    head_dim = d_model // n_heads
    valid = jnp.arange(m)[None, :] < q_lens[:, None]  # (B, M)
    pos = (kv_lens - q_lens)[:, None] + jnp.arange(m)[None, :]
    # invalid positions' page index may run past the table width — XLA
    # clamps the gather, and the mask below discards the clamped id
    page_idx = jnp.where(valid,
                         jnp.take_along_axis(
                             tables,
                             jnp.clip(pos // page_size, 0,
                                      tables.shape[1] - 1), axis=1), 0)
    slot_idx = jnp.where(valid, pos % page_size, 0)

    for layer in range(n_layers):
        p = params[f"layer{layer}"]
        h = _rmsnorm(x, p["ln1"]["scale"])
        qkv = h @ qmat(p["wqkv"], compute_dtype)
        q, knew, vnew = split_qkv(qkv, b, m, n_heads, n_kv, head_dim)
        if rope_theta:
            q = apply_rope(q, pos, rope_theta)
            knew = apply_rope(knew, pos, rope_theta)
        kv_pool = kv_pool.at[layer, page_idx, 0, slot_idx].set(
            knew.astype(kv_pool.dtype))
        kv_pool = kv_pool.at[layer, page_idx, 1, slot_idx].set(
            vnew.astype(kv_pool.dtype))
        if use_kernel:
            # pallas ragged walk over the block tables (one program for
            # every segment mix; sharded on KV-heads under a mesh)
            from tpulab.ops.ragged_attention import ragged_paged_attention
            gk, nk = kernel_geometry or (None, None)
            attn = ragged_paged_attention(
                q, kv_pool[layer], tables, q_lens, kv_lens,
                mesh=mesh, g_pages=gk, nbuf=nk,
            ).astype(compute_dtype).reshape(b, m, d_model)
        else:
            # gather-after-scatter: token m sees cached context + the
            # segment's own writes up to its position (global causality)
            attn = _gather_attend(q, kv_pool[layer, :, 0],
                                  kv_pool[layer, :, 1],
                                  tables, pos, compute_dtype)
        x = x + attn @ qmat(p["wo"], compute_dtype)
        h2 = _rmsnorm(x, p["ln2"]["scale"])
        x = x + _dense_ffn(p, h2, compute_dtype).astype(x.dtype)

    if last_only:
        # only each lane's last valid token seeds a pick — run the
        # vocab-sized head over ONE row per lane (paged_extend's trick,
        # batched)
        xl = jnp.take_along_axis(
            x, jnp.maximum(q_lens - 1, 0)[:, None, None], axis=1)[:, 0]
        xl = _rmsnorm(xl, params["final_norm"]["scale"])
        return _lm_head(params, xl), kv_pool
    x = _rmsnorm(x, params["final_norm"]["scale"])
    return _lm_head(params, x), kv_pool


def paged_mixed_step(params, kv_pool, tables, seq, q_lens, kv_lens,
                     temps, seeds, n_heads: int, n_layers: int,
                     compute_dtype, use_kernel: bool = False,
                     n_kv_heads: Optional[int] = None,
                     rope_theta: Optional[float] = None,
                     mesh=None,
                     kernel_geometry: Optional[tuple] = None):
    """One mixed prefill+decode round: a ragged forward over per-lane
    segments plus each lane's next-token pick, in ONE dispatch.

    Prefilling lanes carry their prompt chunk (``q_lens = chunk``),
    decoding lanes carry their current token (``q_lens = 1``); every
    lane's pick is :func:`_device_sample_token` on its LAST valid
    position's logits at position ``kv_lens - 1`` — exactly the decode
    tick's stream for decode lanes and exactly the prefill first-token
    stream (position ``t - 1``) for lanes finishing their prompt, so
    one request is one (seed, position)-keyed stream regardless of
    which dispatch kind served it.  The caller consumes picks only for
    lanes that emit this round (a mid-prompt chunk's pick is discarded;
    device sampling is stateless, so a discarded pick costs nothing).

    Returns ``(next_tokens (B,) i32, logprobs (B,) f32, last_logits
    (B, vocab), kv_pool)`` — ``last_logits`` stays device-resident
    unless a host-sampled lane fetches its row.
    """
    import jax
    import jax.numpy as jnp

    last, kv_pool = paged_ragged_forward(
        params, kv_pool, tables, seq, q_lens, kv_lens,
        n_heads=n_heads, n_layers=n_layers, compute_dtype=compute_dtype,
        use_kernel=use_kernel, n_kv_heads=n_kv_heads,
        rope_theta=rope_theta, mesh=mesh,
        kernel_geometry=kernel_geometry, last_only=True)
    pos_last = jnp.maximum(kv_lens - 1, 0)
    next_tokens = jax.vmap(_device_sample_token)(
        last, temps, seeds.astype(jnp.uint32), pos_last)
    logp_rows = jax.nn.log_softmax(last.astype(jnp.float32), axis=-1)
    logprobs = jnp.take_along_axis(logp_rows, next_tokens[:, None],
                                   axis=-1)[:, 0]
    return next_tokens, logprobs, last, kv_pool


def paged_speculative_block(params, draft_params, kv_pool, tables,
                            draft_tables, lengths, tokens, active, temps,
                            seeds, steps_rem, stop_ids,
                            n_heads: int, n_layers: int,
                            draft_n_heads: int, draft_n_layers: int,
                            compute_dtype, k: int = 4,
                            n_kv_heads: Optional[int] = None,
                            draft_n_kv_heads: Optional[int] = None,
                            rope_theta: Optional[float] = None,
                            use_kernel: bool = False, mesh=None,
                            kernel_geometry: Optional[tuple] = None):
    """Speculative decode: draft-propose + target-verify + per-lane
    accept/reject, ALL inside one device dispatch.

    A small draft model proposes ``k`` tokens per lane (a ``lax.scan``
    of single-token draft steps through a SECOND page table on the same
    fused pool), the target model verifies the current token plus all k
    proposals in ONE batched forward (:func:`_paged_verify_forward`),
    and acceptance runs on device: each lane emits the longest prefix of
    proposals matching the target's own choices, plus the target's
    correction (or bonus) token — so emitted tokens are EXACTLY the
    non-speculative stream, and one dispatch emits up to ``k + 1``
    tokens instead of ``k``.  The target's "choice" is
    :func:`_device_sample_token` at each position — greedy argmax for
    temp==0 lanes, and for device-sampled lanes the same
    (seed, position)-folded stream plain blocks use, so token parity is
    bit-exact in both modes.  The draft proposes through the SAME
    sampling function on its own logits (a perfect draft then reaches
    full acceptance under sampling too).

    Stop-mask machinery matches :func:`paged_decode_block`: a stop token
    is emitted as the lane's final token and truncates the emission; the
    per-lane steps-remaining budget caps it, and writes past the budget
    route to the scratch page (so a full-K block at the tail of a
    request can never write past the positions its reservation covers).
    Dead lanes emit nothing and write only scratch.  The draft scan runs
    ``k + 1`` iterations (last proposal discarded) so a fully-accepted
    round leaves no hole in the draft KV — the dense
    :class:`~tpulab.engine.speculative.SpeculativeGenerator` trick.
    Rejected proposals leave stale K/V past the accepted horizon in both
    tables; positions only advance, so every stale slot is overwritten
    before any later query may attend it.

    The CALLER pre-allocates BOTH tables to cover positions
    ``lengths .. lengths + k`` (see ``_reserve_spec_pages``).
    ``use_kernel`` routes attention on BOTH models through the ragged
    pallas kernel family (draft proposal steps at q=1, the verify
    forward at q=k+1 — the PR 7 follow-up retired); the XLA gather is
    the fallback, and under a ``mesh`` the kernel shards on KV heads.

    Returns ``(tokens (B, k+1) i32, logprobs (B, k+1) f32, emitted
    (B, k+1) bool prefix mask, lengths (B,), last_tokens (B,), live
    (B,), steps_rem (B,), drafted (B,) i32, accepted (B,) i32,
    kv_pool)``.
    """
    import jax
    import jax.numpy as jnp

    seeds = seeds.astype(jnp.uint32)

    # 1) draft proposes k tokens per lane through the second page table;
    #    iterations past a lane's step budget write only scratch (their
    #    proposals can never be emitted)
    def dbody(carry, i):
        kv, tok = carry
        nt, _lp, _lg, kv = paged_decode_step(
            draft_params, kv, draft_tables, lengths + i, tok,
            active & (i < steps_rem),
            n_heads=draft_n_heads, n_layers=draft_n_layers,
            compute_dtype=compute_dtype, use_kernel=use_kernel,
            n_kv_heads=draft_n_kv_heads, rope_theta=rope_theta,
            temps=temps, seeds=seeds, kernel_geometry=kernel_geometry,
            mesh=mesh)
        return (kv, nt), nt

    (kv_pool, _), props = jax.lax.scan(dbody, (kv_pool, tokens),
                                       jnp.arange(k + 1))
    drafts = props[:k].T                               # (B, k)

    # 2) target verifies [cur, d_0..d_{k-1}] in ONE batched ragged
    #    forward (q_lens = the valid prefix per lane); position j's
    #    write is real only while the lane can still emit token j
    #    (emitted n <= steps_rem, and query j consumes writes 0..j only,
    #    so masking j >= steps_rem discards nothing live)
    seq = jnp.concatenate([tokens[:, None], drafts], axis=1)  # (B, k+1)
    q_lens = jnp.where(active,
                       jnp.minimum(k + 1, jnp.maximum(steps_rem, 0)), 0)
    logits, kv_pool = paged_ragged_forward(
        params, kv_pool, tables, seq, q_lens, lengths + q_lens,
        n_heads=n_heads, n_layers=n_layers, compute_dtype=compute_dtype,
        use_kernel=use_kernel, n_kv_heads=n_kv_heads,
        rope_theta=rope_theta, mesh=mesh, kernel_geometry=kernel_geometry)

    # 3) the target's own choice at every position — the same sampling
    #    stream as plain blocks, so the output is bit-identical
    pos = lengths[:, None] + jnp.arange(k + 1)[None, :]
    cand = jax.vmap(jax.vmap(_device_sample_token,
                             in_axes=(0, None, None, 0)))(
        logits, temps, seeds, pos)                      # (B, k+1)
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    lps = jnp.take_along_axis(lsm, cand[..., None], axis=-1)[..., 0]

    # 4) accept/reject + stop-mask, on device: emit the agreeing prefix
    #    + correction, truncated by stop tokens and steps remaining
    agree = drafts == cand[:, :k]
    acc = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(axis=1)  # (B,)
    avail = acc + 1                     # accepted prefix + correction
    hit = (cand[:, :, None] == stop_ids[:, None, :]).any(axis=2)
    first_stop = jnp.argmax(hit, axis=1)
    stop_cap = jnp.where(hit.any(axis=1), first_stop + 1, k + 1)
    n = jnp.minimum(jnp.minimum(avail, stop_cap), steps_rem)
    n = jnp.where(active, n, 0)
    emitted = jnp.arange(k + 1)[None, :] < n[:, None]   # (B, k+1)
    lengths = lengths + n
    last = jnp.take_along_axis(cand, jnp.maximum(n - 1, 0)[:, None],
                               axis=1)[:, 0]
    tokens = jnp.where(n > 0, last, tokens).astype(jnp.int32)
    steps_rem = steps_rem - n
    stopped = hit.any(axis=1) & (stop_cap <= n)
    live = active & (steps_rem > 0) & ~stopped
    drafted = jnp.where(active, k, 0)
    accepted = jnp.where(active, jnp.minimum(acc, n), 0)
    return (cand.astype(jnp.int32), lps, emitted, lengths, tokens, live,
            steps_rem, drafted, accepted, kv_pool)


def paged_prefill(params, kv_pool, tables, tokens, valid_len,
                  n_heads: int, n_layers: int, compute_dtype,
                  n_kv_heads: Optional[int] = None,
                  rope_theta: Optional[float] = None,
                  attention_fn=None):
    """Fused prefill: ONE causal forward over the (padded) prompt, with each
    layer's K/V scattered straight into the lane's pages.

    tokens (1, T_pad) int32 (padded tail arbitrary), valid_len scalar int32,
    tables (MP,) page ids for this lane.  Padded positions scatter to the
    reserved scratch page 0.  Returns (last-valid-token logits (vocab,),
    kv_pool) — the fused pool donated by the caller.
    """
    import jax
    import jax.numpy as jnp
    from tpulab.models.transformer import (causal_attention,
                                           transformer_forward_collect_kv)

    page_size = kv_pool.shape[3]
    t_pad = tokens.shape[1]
    logits, kvs = transformer_forward_collect_kv(
        params, tokens, n_heads=n_heads, n_layers=n_layers,
        compute_dtype=compute_dtype, n_kv_heads=n_kv_heads,
        rope_theta=rope_theta,
        attention_fn=attention_fn or causal_attention)
    pos = jnp.arange(t_pad)
    valid = pos < valid_len
    page_idx = jnp.where(valid, tables[pos // page_size], 0)  # scratch if pad
    slot_idx = jnp.where(valid, pos % page_size, 0)
    for layer, (k, v) in enumerate(kvs):
        kv_pool = kv_pool.at[layer, page_idx, 0, slot_idx].set(
            k[0].astype(kv_pool.dtype))
        kv_pool = kv_pool.at[layer, page_idx, 1, slot_idx].set(
            v[0].astype(kv_pool.dtype))
    last = logits[0, valid_len - 1]
    return last, kv_pool


def paged_extend(params, kv_pool, tables, tokens, start, valid_total,
                 n_heads: int, n_layers: int, compute_dtype,
                 n_kv_heads: Optional[int] = None,
                 rope_theta: Optional[float] = None):
    """Chunked/tail prefill against EXISTING paged context.

    One fused forward over M tail tokens (positions ``start ..
    start+M-1``) for a single lane whose positions ``[0, start)`` are
    already resident in the pool (prefix-cache hits or earlier chunks of a
    chunked prefill).  Per layer the tail K/V scatter into their pages
    first, then attention gathers the lane's WHOLE block table — the
    gather-after-scatter sees cached prefix and tail together, so the mask
    is just global causality (tail token m attends position j iff
    ``j <= start+m``).

    tokens (1, M_pad) int32 (padded tail arbitrary); start scalar int32
    (page-aligned: the tail must never write into a shared prefix page);
    valid_total scalar int32 = true total length (prompt so far + tail);
    tables (MP,) page ids covering all of it.  Returns (logits of the last
    valid token (vocab,), kv_pool) — the fused pool donated by the caller.
    """
    import jax.numpy as jnp
    from tpulab.models.transformer import (_dense_ffn, _lm_head, _rmsnorm,
                                           apply_rope, qmat, split_qkv)

    n_kv = n_kv_heads or n_heads
    page_size = kv_pool.shape[3]
    m_pad = tokens.shape[1]
    emb = params["embed"].astype(compute_dtype)
    x = emb[tokens]                                   # (1, M_pad, D)
    d_model = x.shape[-1]
    head_dim = d_model // n_heads
    pos = start + jnp.arange(m_pad)                   # global positions
    valid = pos < valid_total
    page_idx = jnp.where(valid, tables[pos // page_size], 0)  # pad -> scratch
    slot_idx = jnp.where(valid, pos % page_size, 0)

    for layer in range(n_layers):
        p = params[f"layer{layer}"]
        h = _rmsnorm(x, p["ln1"]["scale"])
        qkv = h @ qmat(p["wqkv"], compute_dtype)
        q, knew, vnew = split_qkv(qkv, 1, m_pad, n_heads, n_kv, head_dim)
        if rope_theta:
            q = apply_rope(q, pos, rope_theta)
            knew = apply_rope(knew, pos, rope_theta)
        kv_pool = kv_pool.at[layer, page_idx, 0, slot_idx].set(
            knew[0].astype(kv_pool.dtype))
        kv_pool = kv_pool.at[layer, page_idx, 1, slot_idx].set(
            vnew[0].astype(kv_pool.dtype))
        # gather-after-scatter: context = cached prefix + this tail
        attn = _gather_attend(q, kv_pool[layer, :, 0], kv_pool[layer, :, 1],
                              tables[None], pos[None], compute_dtype)
        x = x + attn @ qmat(p["wo"], compute_dtype)
        h2 = _rmsnorm(x, p["ln2"]["scale"])
        x = x + _dense_ffn(p, h2, compute_dtype).astype(x.dtype)

    # only the last valid token's logits are ever consumed — run the
    # vocab-sized head over ONE row, not all M_pad rows
    x_last = x[0, valid_total - 1 - start][None]      # (1, D)
    x_last = _rmsnorm(x_last, params["final_norm"]["scale"])
    last = _lm_head(params, x_last)[0]                # (vocab,)
    return last, kv_pool


class PrefixCache:
    """Prompt prefix cache over the paged pool (full-page granularity).

    Maps a digest of the token prefix ``prompt[:(i+1)*S]`` to the page
    holding that S-token span's K/V.  A hit lets a new request *share* the
    cached pages (``PagedKVPool.add_ref``) and prefill only the tail via
    :func:`paged_extend` — the paged-serving time-to-first-token
    optimization for shared system prompts / few-shot preambles.

    Safety: only FULL prompt pages enter the cache, and a request's write
    region (tail prefill + decode appends) always sits at page boundaries
    at-or-after its shared prefix — shared pages are read-only by
    construction, so no copy-on-write is needed.  The last prompt token is
    never served from cache (its logits seed generation), which the
    lookup guarantees by capping reuse at ``(t-1) // S`` pages.

    LRU: entries hold one pool reference each; under pool pressure the
    batcher evicts from the cold end.  Single-threaded by design — only
    the scheduler thread touches it (documented invariant).
    """

    def __init__(self, pool: PagedKVPool):
        from collections import OrderedDict
        self._pool = pool
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0       # pages served from cache
        self.misses = 0     # full prompt pages computed fresh
        #: optional host-tier hooks (set by the batcher when kv_offload is
        #: on): ``on_evict(digest, page)`` fires on pressure eviction
        #: BEFORE the page is released (demotion window);
        #: ``promote_fn(digest) -> Optional[page]`` may resurrect a
        #: demoted entry during lookup — the returned page's single pool
        #: reference belongs to the cache.
        self.on_evict = None
        self.promote_fn = None
        self.host_promotions = 0  # lookup pages served from the host tier

    @staticmethod
    def _digests(prompt: np.ndarray, page_size: int, n_pages: int):
        import hashlib
        # incremental chain: extend one page per step and snapshot — O(t)
        # total bytes hashed (a from-scratch prefix hash per page is O(t^2))
        out = []
        raw = np.ascontiguousarray(prompt, np.int32)
        h = hashlib.blake2b(digest_size=16)
        for i in range(n_pages):
            h.update(raw[i * page_size:(i + 1) * page_size].tobytes())
            out.append(h.copy().digest())
        return out

    def lookup(self, prompt: np.ndarray, page_size: int):
        """Longest cached full-page prefix of ``prompt``.

        Returns (shared_pages, digests) where ``shared_pages`` are
        ref-bumped for the caller (caller owns one release each) and
        ``digests`` covers every full prompt page (for insert later).
        Hit/miss accounting is the CALLER's job (count_lookup) once the
        prefill actually proceeds — a page-pressure retry re-runs lookup
        and must not double-count.
        """
        t = len(prompt)
        cacheable = max(0, (t - 1) // page_size)  # last token never cached
        digests = self._digests(prompt, page_size,
                                t // page_size)
        shared: List[int] = []
        for i in range(cacheable):
            page = self._entries.get(digests[i])
            if page is None and self.promote_fn is not None:
                # spill-backed cache: a demoted entry can come back from
                # the host tier mid-lookup (the hook allocates + uploads;
                # the new page's one ref is the cache's)
                page = self.promote_fn(digests[i])
                if page is not None:
                    self._entries[digests[i]] = page
                    self.host_promotions += 1
            if page is None:
                break
            self._entries.move_to_end(digests[i])
            self._pool.add_ref(page)
            shared.append(page)
        return shared, digests

    def count_lookup(self, n_shared: int, n_full_pages: int) -> None:
        """Record one *successful* lookup's hit/miss stats."""
        self.hits += n_shared
        self.misses += max(0, n_full_pages - n_shared)

    def coverage(self, prompt, page_size: int) -> int:
        """Cached-page count of ``prompt``'s full-page prefix WITHOUT the
        lookup's side effects (no LRU touch, no ref bump, no host-tier
        promotion) — the fleet KV fabric's local-hit probe
        (tpulab.kvfabric): deciding whether a remote pull is worth it
        must not perturb the cache it is measuring.  Advisory by nature:
        the RPC thread calls it while the scheduler mutates entries, so
        the answer can be one tick stale — staleness in either direction
        only costs work (a skipped pull, a redundant one), never
        correctness: the real ``lookup`` still runs at prefill."""
        t = len(prompt)
        cacheable = max(0, (t - 1) // page_size)
        if cacheable == 0:
            return 0
        digests = self._digests(np.asarray(prompt, np.int32), page_size,
                                cacheable)
        n = 0
        for d in digests:
            if d not in self._entries:
                break
            n += 1
        return n

    def insert(self, digests: List[bytes], pages: List[int]) -> None:
        """Publish a prefilled request's full prompt pages (one extra pool
        ref each, owned by the cache).  Digest collisions with existing
        entries keep the incumbent (both pages hold identical K/V)."""
        for dig, page in zip(digests, pages):
            if dig in self._entries:
                self._entries.move_to_end(dig)
                continue
            self._pool.add_ref(page)
            self._entries[dig] = page

    def evict_one(self) -> bool:
        """Drop the coldest entry (its pool ref); True if something fell."""
        if not self._entries:
            return False
        _, page = self._entries.popitem(last=False)
        self._pool.release_pages([page])
        return True

    def evict_for_alloc(self) -> bool:
        """Evict the coldest entry whose page would actually FREE (cache
        holds the only reference).  Entries shared with active requests
        (refcount > 1) are skipped: dropping them frees nothing now, so
        transient pool pressure must not wipe them.  False when no
        eviction can produce a free page."""
        for dig, page in self._entries.items():  # OrderedDict: cold first
            if self._pool.refcount(page) == 1:
                del self._entries[dig]
                if self.on_evict is not None:
                    # demotion window: the hook's device-side copy is
                    # dispatched before the release below, so a recycled
                    # page's later writes are stream-ordered after it
                    try:
                        self.on_evict(dig, page)
                    except Exception:  # demotion is best-effort
                        import logging
                        logging.getLogger("tpulab.engine").exception(
                            "prefix-cache demotion hook failed")
                self._pool.release_pages([page])
                return True
        return False

    def clear(self) -> None:
        while self.evict_one():
            pass

    def drop_all(self) -> None:
        """Forget every entry WITHOUT touching the pool — for use after
        ``PagedKVPool.reset()`` already rebuilt the free list (releasing
        into a reset pool would double-free)."""
        self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)


class SamplingParams:
    """Token selection policy (greedy by default).

    ``device=False`` (default): host-side temperature / top-k sampling
    with a per-request numpy PRNG — requires fetching the lane's full
    (vocab,) logits row every tick.

    ``device=True``: TPU-first temperature sampling computed ON CHIP
    (Gumbel-max over the logits with a per-lane key folded from
    (seed, position)) — the tick fetches only (B,) token ids, never the
    logits.  Reproducible per request (the key depends only on seed and
    position, not batch-mates or preemption) but a DIFFERENT stream than
    the host PRNG.  ``top_k`` / ``top_p`` are host-side features:
    device=True with either set is rejected (per-lane truncation is not
    a static compile-time shape).

    ``top_p`` (nucleus sampling, 0 < top_p < 1) keeps the smallest set
    of tokens whose probabilities sum to at least top_p; composes with
    ``top_k`` (k-truncation first, then the nucleus), the standard order.
    """

    __slots__ = ("temperature", "top_k", "top_p", "device", "seed", "_rng")

    def __init__(self, temperature: float = 0.0, top_k: int = 0,
                 seed: Optional[int] = None, device: bool = False,
                 top_p: float = 0.0):
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        if device and (top_k > 0 or 0.0 < top_p < 1.0):
            raise ValueError("device sampling does not support top_k/top_p "
                             "(per-lane truncation is not a static shape); "
                             "use host sampling")
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.device = device
        if seed is None:
            # full 64-bit draw: device sampling keys on both seed words,
            # a 31-bit default would zero the hi word for every unseeded
            # request and shrink the stream space
            seed = int(np.random.default_rng().integers(
                0, 2**64, dtype=np.uint64))
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)

    def pick(self, logits: np.ndarray) -> int:
        """Select the next token from a (vocab,) logits row."""
        if self.temperature == 0.0:
            return int(logits.argmax())
        z = logits.astype(np.float64) / self.temperature
        if self.top_k > 0 and self.top_k < z.shape[0]:
            kth = np.partition(z, -self.top_k)[-self.top_k]
            z = np.where(z >= kth, z, -np.inf)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        if 0.0 < self.top_p < 1.0:
            # nucleus: smallest prob-descending prefix summing >= top_p
            order = np.argsort(p)[::-1]
            csum = np.cumsum(p[order])
            cut = int(np.searchsorted(csum, self.top_p)) + 1
            mask = np.zeros_like(p, dtype=bool)
            mask[order[:cut]] = True
            p = np.where(mask, p, 0.0)
            p /= p.sum()
        return int(self._rng.choice(z.shape[0], p=p))


class _PagedRequest:
    __slots__ = ("prompt", "steps", "future", "tokens_out", "pages",
                 "length", "pending_prompt", "on_token", "cancelled",
                 "sampling", "priority", "resumed", "admit_seq",
                 "stop_tokens", "want_logprobs", "logprobs_out", "deadline",
                 "trace_id", "t_submit", "t_prefill0", "t_first", "t_last",
                 "chunk_t0", "chunk_start", "kv_handle", "export_digest",
                 "draft_pages", "draft_len", "spec_enabled", "spec_ewma",
                 "spec_drafted", "spec_accepted", "spec_probe_in",
                 "spec_probing", "tenant", "lane", "fl", "batch",
                 "pf_started", "pf_digests", "pf_shared", "pf_t0")

    def __init__(self, prompt: np.ndarray, steps: int, on_token=None,
                 sampling: Optional[SamplingParams] = None,
                 priority: int = 0, stop_tokens=None,
                 logprobs: bool = False, deadline: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 tenant: Optional[str] = None, batch: bool = False):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.steps = steps
        self.future: Future = Future()
        self.tokens_out: List[int] = []
        self.pages: List[int] = []
        self.length = 0
        self.pending_prompt = list(self.prompt)
        self.on_token = on_token
        self.cancelled = False
        self.sampling = sampling or SamplingParams()
        self.priority = priority
        #: offline batch lane (docs/SERVING.md "Offline batch lane"):
        #: batch requests rank strictly below EVERY online request —
        #: they queue behind all online arrivals regardless of priority
        #: and are the first preemption victims when an online arrival
        #: needs a lane or pages.  Within the batch class, priority and
        #: FIFO order apply as usual.
        self.batch = bool(batch)
        self.resumed = False     # preempted mid-decode; resume skips the
        #                          prefill pick (its token was already emitted)
        self.kv_handle = None    # host-tier KV snapshot of a preempted lane
        #                          (kvcache.SwapHandle); resume swaps it back
        #                          in instead of re-prefilling
        self.export_digest = None  # disagg: demote finished KV to the host
        #                            tier under ("ship", digest) at release
        self.admit_seq = -1      # admission order (preemption tie-break)
        self.stop_tokens = frozenset(int(t) for t in (stop_tokens or ()))
        self.want_logprobs = logprobs
        self.logprobs_out: List[float] = []
        #: absolute monotonic expiry (None = unbounded); the scheduler's
        #: per-iteration sweep cancels expired requests before their next
        #: step, freeing the lane and pages
        self.deadline = deadline
        # -- speculative decode lane state (second page table) --------------
        self.draft_pages: List[int] = []  # draft KV page ids (never shared)
        self.draft_len = 0         # context positions the draft KV covers
        self.spec_enabled = True   # False: plain blocks (chaos verify trip
        #                            degrades for the REST of the request;
        #                            an acceptance-EWMA degrade is transient
        #                            — see spec_probe_in)
        self.spec_probe_in = None  # plain dispatches until the next probe
        #                            block re-tries speculation (None = no
        #                            probe scheduled: never degraded, or
        #                            degraded permanently by chaos)
        self.spec_probing = False  # the next/current spec dispatch is a
        #                            probe: its acceptance decides recovery
        self.spec_ewma = 1.0       # rolling acceptance (optimistic start)
        self.spec_drafted = 0      # draft proposals verified for this lane
        self.spec_accepted = 0     # of those, emitted (accepted) ones
        # -- request-lifecycle telemetry (trace spans + latency metrics) ----
        self.trace_id = trace_id
        #: admission tenant (flight-recorder / debugz attribution only —
        #: the scheduler never reads it)
        self.tenant = tenant
        #: last lane this request occupied (-1 = never admitted)
        self.lane = -1
        #: flight-recorder per-request detail (None = recorder disarmed:
        #: the scheduling hot path pays one None check per site)
        self.fl: Optional[dict] = None
        # -- ragged dispatch plan: multi-round chunked-prefill state --------
        self.pf_started = False      # pages secured, chunks may dispatch
        self.pf_digests = None       # full-prompt-page digests (insert at
        #                              prompt completion)
        self.pf_shared = 0           # prefix-cache pages served shared
        self.pf_t0: Optional[float] = None  # this prefill's start (spans)
        self.t_submit = _time.perf_counter()
        self.t_prefill0: Optional[float] = None  # first prefill start
        self.t_first: Optional[float] = None     # first emitted token
        self.t_last: Optional[float] = None      # latest emitted token
        self.chunk_t0: Optional[float] = None    # open decode-chunk start
        self.chunk_start = 0                     # first token idx in chunk

    def finished(self) -> bool:
        """steps exhausted, or the last emitted token is a stop token
        (which stays in the output, ending it)."""
        return bool(self.tokens_out) and (
            len(self.tokens_out) >= self.steps
            or self.tokens_out[-1] in self.stop_tokens)


#: process-level memo of jitted engine programs (see
#: ContinuousBatcher._jit): identical-geometry engines share one jitted
#: callable and therefore one compiled-program cache.  Bounded by the
#: process's program-config variety; entries hold compiled executables,
#: never parameter or pool buffers (those are traced arguments).
_JIT_MEMO: Dict[Any, Any] = {}
_JIT_MEMO_LOCK = threading.Lock()


class ContinuousBatcher:
    """Continuous-batching scheduler over the paged pool.

    ``submit(prompt, steps) -> Future[list[int]]``; a background scheduler
    thread runs one fused decode dispatch per iteration over up to
    ``lanes`` concurrent requests, admitting queued requests whenever a
    lane (and pages) free up — no head-of-line draining.
    ``cancel(future)`` aborts a request and frees its lane/pages at the
    next dispatch boundary.

    Multi-step fused decode: each dispatch covers an adaptive K decode
    ticks (``decode_block`` is the ceiling) chained on device via
    :func:`paged_decode_block`, so the host pays ONE dispatch + ONE
    blocking fetch per K tokens instead of per token — off-chip the
    per-token cost is the link RTT, and K amortizes it.  Greedy and
    device-sampled lanes run at full K (sampling and the EOS /
    steps-remaining stop mask live on device); any host-sampled
    (``top_k``/``top_p``) lane in the batch drops the whole batch to K=1
    (its sampling needs the logits row on host every token).  K adapts
    down to 1-2 when a lane's deadline is tight or a streaming consumer
    is attached with no queue pressure, so interactive TTFT/ITL does not
    regress; per-token ``on_token`` callbacks still fire in order, and
    cancellation/deadline sweeps act at block boundaries (a request stops
    within at most one block of the sweep observing it).

    Speculative decoding (``draft_params=``, docs/PERFORMANCE.md): a
    small draft model (e.g. :func:`tpulab.models.transformer.
    early_exit_draft`) rides the SAME paged pool through a second
    per-lane page table; each fused dispatch drafts K tokens, verifies
    them in one batched target forward, and emits up to K+1 accepted
    tokens — multiplying the block amortization by the acceptance rate
    with bit-identical output.  Host-sampled lanes never speculate, and
    lanes degrade to plain blocks on low acceptance, chaos verify trips,
    or draft-table pool pressure.

    Sharded serving (``mesh=``, tpulab.parallel): a ``{"model": M}`` mesh
    runs this replica tensor-parallel over M devices — params placed by
    the Megatron-TP partition rules, the KV page store sharded on the
    KV-heads dim (page tables stay replicated), every dispatch a sharded
    jit whose collectives ride INSIDE the fused program.  Emitted tokens
    are bit-identical to mesh=None for greedy and device-sampled
    streams, and the host-sync count per block is unchanged — see
    docs/PERFORMANCE.md "Sharded serving".

    Ragged dispatch plan (``use_kernel=True`` or ``ragged=True``,
    docs/PERFORMANCE.md "Ragged paged attention"): prompts and decode
    lanes advance together through fused mixed rounds
    (:func:`paged_mixed_step`) — per-lane (query_len, kv_len) segments,
    ONE dispatch and one host sync per round, no separate prefill
    programs — and the speculative verify forward rides the same
    ragged kernel family.  Tokens are bit-exact vs the legacy split
    dispatch (``use_kernel=False``, the escape hatch), mesh on or off.

    Tiered KV (``kv_offload=``, tpulab.kvcache): preemption swaps the
    victim's KV pages to a budgeted host-RAM tier (async, write-behind)
    and resume swaps them back with ZERO prefill dispatches; prefix-cache
    entries evicted under pool pressure demote to the host tier and
    promote back on the next lookup hit.  Every degraded swap falls back
    to the exact re-prefill/recompute path — see docs/PERFORMANCE.md.
    """

    #: explicit capability marker for routers (e.g. the Generate RPC)
    continuous_batching = True

    #: decode tokens per trace span ("each decode chunk"): per-token spans
    #: would swamp the bounded event ring at serving rates.  K>1 decode
    #: flushes one span per BLOCK instead (block-sized decode spans).
    TRACE_DECODE_CHUNK = 8

    #: fused-decode block sizes: the adaptive K snaps DOWN onto this menu
    #: so the jit cache stays tiny (one compiled scan per size in use)
    BLOCK_K_MENU = (1, 2, 4, 8, 16)

    #: shortest max_len at which use_kernel=None auto-selects the pallas
    #: kernel on TPU (below this the only live capture shows the XLA
    #: gather ahead; see __init__'s auto-select comment)
    KERNEL_AUTO_MIN_CTX = 8192

    def __init__(self, params, n_heads: int, n_layers: int,
                 pool: Optional[PagedKVPool] = None, lanes: int = 4,
                 max_len: int = 256, page_size: int = 16,
                 n_pages: int = 0, compute_dtype=None, device=None,
                 use_kernel: Optional[bool] = None,
                 n_kv_heads: Optional[int] = None,
                 rope_theta: Optional[float] = None,
                 prefix_cache: bool = False,
                 prefill_chunk: Optional[int] = None,
                 kv_dtype=None,
                 prefill_flash: Optional[bool] = None,
                 trace=None, metrics=None,
                 decode_block: int = 8,
                 kv_offload=None,
                 draft_params=None,
                 draft_n_layers: Optional[int] = None,
                 draft_n_heads: Optional[int] = None,
                 draft_n_kv_heads: Optional[int] = None,
                 spec_accept_floor: float = 0.35,
                 mesh=None, hbm=None, flight=None,
                 ragged: Optional[bool] = None,
                 kv_publish: bool = False):
        import jax
        import jax.numpy as jnp

        compute_dtype = compute_dtype or jnp.bfloat16
        # KV-cache quantization: pages may store a NARROWER dtype than the
        # compute path (e.g. kv_dtype=jnp.float8_e4m3fn under bf16 compute
        # halves KV HBM *and* decode bandwidth — the decode tick is
        # KV-bandwidth-bound).  Writes round on scatter, reads upcast in
        # the gather/kernel; attention math stays in f32 either way.
        kv_dtype = kv_dtype or compute_dtype
        n_kv = n_kv_heads or n_heads
        self.lanes = lanes
        self.max_len = max_len
        self.page_size = page_size
        self.max_pages = (max_len + page_size - 1) // page_size
        from tpulab.models.transformer import weight_shape
        d_model = weight_shape(params["layer0"]["wqkv"])[0]
        #: id-validation bound (public: the Generate RPC checks it too)
        self.vocab = int(weight_shape(params["embed"])[0])
        # +1: page 0 is the reserved scratch page.  GQA pools store the
        # compact n_kv_heads form — KV HBM shrinks by n_heads/n_kv_heads.
        self._owns_pool = pool is None
        if pool is not None and kv_dtype != compute_dtype \
                and pool.dtype != kv_dtype:
            raise ValueError(
                f"kv_dtype={jnp.dtype(kv_dtype).name} conflicts with the "
                f"provided pool's dtype {jnp.dtype(pool.dtype).name}")
        self.pool = pool or PagedKVPool(
            n_pages or self.max_pages * lanes + 1, page_size, n_layers,
            n_kv, d_model // n_heads, kv_dtype, device, mesh=mesh)
        if pool is not None and mesh is not None and pool.mesh is not mesh:
            raise ValueError("provided pool was built on a different mesh "
                             "than the batcher's")
        # unified HBM economy (tpulab.hbm, docs/PERFORMANCE.md "HBM
        # economy"): with an arbiter the batcher is the KV TENANT — the
        # pool's page store becomes elastic (a KV burst wins bytes from
        # cold models via the arbiter's pressure protocol; a hot model's
        # acquire squeezes idle KV down to the host tier), and every jit
        # this engine compiles records its scratch with the ledger.  Set
        # before the first _jit so scratch measuring can wrap them.
        self.hbm = hbm
        self._hbm_reclaim_bytes = 0  # outstanding arbiter reclaim target
        self.hbm_grows = 0           # pool grow ops granted by the arbiter
        self.hbm_shrinks = 0         # pool shrink ops under pressure
        self.hbm_demotions = 0       # lanes demoted (preempted) by pressure
        #: elastic pool sizes snap to a geometric ladder off the initial
        #: size (n0, 2*n0, 4*n0, ...) — every pool shape recompiles the
        #: fused programs, so sizes must come from a bounded menu the
        #: warm-up can cover (the BLOCK_K_MENU / pow2-prefill-bucket
        #: discipline applied to capacity)
        self._hbm_pool_base = self.pool.n_pages
        self._hbm_starved_passes = 0  # hold-and-wait breaker streak
        if hbm is not None:
            self.pool.prefer_low_pages = True
        # sharded serving (docs/PERFORMANCE.md "Sharded serving"): with a
        # ``mesh`` ({"model": M}, tpulab.parallel) one replica serves a
        # model sharded over M devices — params placed by the Megatron-TP
        # rules (wqkv/w1/w3/lm_head column-, wo/w2 row-parallel), the KV
        # page store sharded on the KV-heads dim, and every dispatch a
        # sharded jit with explicit in/out shardings so XLA inserts the
        # psums INSIDE the fused program: the one-host-sync-per-block
        # contract and device-side sampling are unchanged, and per-lane
        # carry/state stays replicated.  mesh=None is bit-for-bit today's
        # single-device path.
        self.mesh = getattr(self.pool, "mesh", None)
        if self.hbm is not None and self.mesh is not None:
            # PR 11's named follow-up, closed as an explicit contract:
            # the elastic pool's grow/shrink per-shard accounting is
            # UNTESTED under a mesh (the ladder recompiles sharded
            # programs per size and concat/slice re-infer the output
            # sharding) — reject at construction rather than leave a
            # silent corruption path.  ROADMAP item 3 (per-axis ledger)
            # is where this lands properly.
            if self._owns_pool:
                self.pool.close()
            raise NotImplementedError(
                "HBM-arbiter-armed serving (elastic PagedKVPool) under a "
                "mesh is not supported: grow/shrink per-shard accounting "
                "is untested — serve the arbiter single-device, or the "
                "mesh without an arbiter (hbm=None)")
        if self.mesh is not None:
            from tpulab.parallel.sharding import (replicate,
                                                  transformer_param_shardings)
            self._rep = replicate(self.mesh)
            self._param_sh = transformer_param_shardings(params, self.mesh)
            self.params = jax.device_put(params, self._param_sh)
            if prefill_flash:
                raise ValueError(
                    "the pallas flash prefill kernel is single-device; "
                    "mesh serving prefills through the dense or ragged "
                    "paths (prefill_flash must be False or None)")
            prefill_flash = False
        else:
            self._rep = self._param_sh = None
            self.params = jax.device_put(params, self.pool.device)
        n_shards = self.pool.n_shards
        if use_kernel and self.mesh is not None and n_heads % n_shards:
            raise ValueError(
                f"use_kernel under a mesh needs query heads ({n_heads}) "
                f"divisible by the model axis ({n_shards}) — the ragged "
                "kernel shards the page walk on the heads dim")
        if use_kernel is None:
            # auto: the pallas ragged kernel on TPU at LONG contexts only
            # (where the gather fallback's O(lanes*max_len) dense HBM
            # materialization per step is the dominant cost); the XLA
            # gather elsewhere.  The only live capture (round 2, B=8,
            # ctx=2048) showed the kernel at 0.75x the gather, so the
            # short-context default stays gather until a capture proves
            # otherwise (VERDICT r4 weak #2); explicit use_kernel=True
            # overrides.  Under a mesh the kernel shards on the KV-heads
            # dim (shard_map), so the auto pick covers sharded serving
            # too — probed at the PER-SHARD geometry, since one shard's
            # Mosaic compile is the program that must build.  A compile
            # failure must degrade, not kill serving: probe-compile once
            # at the pool's real geometry (page size / heads / head_dim /
            # pool dtype set the VMEM tiles) and fall back if it rejects.
            from tpulab.tpu.platform import is_tpu
            use_kernel = (is_tpu()
                          and max_len >= self.KERNEL_AUTO_MIN_CTX
                          and n_heads % n_shards == 0
                          and _kernel_compiles(
                              n_heads // n_shards, d_model // n_heads,
                              self.pool.page_size, compute_dtype,
                              self.pool.device,
                              n_kv_heads=n_kv // n_shards,
                              kv_dtype=self.pool.dtype))
        self.use_kernel = bool(use_kernel)
        #: ragged dispatch plan (docs/PERFORMANCE.md "Ragged paged
        #: attention"): mixed prefill+decode rounds run as ONE fused
        #: ragged program (paged_mixed_step) instead of per-lane prefill
        #: dispatches followed by a separate decode kind.  Default rides
        #: ``use_kernel`` (the kernel family and the dispatch plan ship
        #: together); ``ragged=True`` forces the unified plan onto the
        #: XLA gather path, ``use_kernel=False`` alone keeps the legacy
        #: split dispatch — the escape hatch.
        self.ragged = self.use_kernel if ragged is None else bool(ragged)
        self._step_kw = dict(n_heads=n_heads, n_layers=n_layers,
                             compute_dtype=compute_dtype,
                             use_kernel=self.use_kernel,
                             n_kv_heads=n_kv, rope_theta=rope_theta,
                             mesh=self.mesh)
        rep, psh = self._rep, self._param_sh
        kvsh = self.pool.kv_sharding
        self._step = self._jit(
            partial(paged_decode_step, **self._step_kw), (1,),
            (psh, kvsh, rep, rep, rep, rep), (rep, kvsh))
        # sampled K=1 variant (positional temps/seeds so the sharded jit
        # can attach in_shardings; identical compiled programs at mesh=None
        # — jit specialized on temps=None vs arrays before too)
        self._step_sampled = self._jit(
            partial(paged_decode_step_sampled, **self._step_kw), (1,),
            (psh, kvsh, rep, rep, rep, rep, rep, rep),
            (rep, rep, rep, kvsh))
        # mixed prefill+decode rounds (the ragged dispatch plan): ONE
        # jitted program respecializes per pow2 segment-width bucket —
        # prefilling lanes ride their chunk and decoding lanes their
        # next token through a single ragged forward + on-device pick
        self._mixed = self._jit(
            partial(paged_mixed_step, **self._step_kw), (1,),
            (psh, kvsh, rep, rep, rep, rep, rep, rep),
            (rep, rep, rep, kvsh))
        if decode_block < 1:
            raise ValueError("decode_block must be >= 1")
        #: max fused-decode steps per dispatch (K): a K-block amortizes the
        #: host<->device round trip over K tokens.  The per-block K is
        #: adaptive (see _pick_block_k) — this is the ceiling; 1 disables
        #: multi-step dispatch entirely.
        self.decode_block = min(int(decode_block), self.BLOCK_K_MENU[-1])
        self._block_cache: Dict[int, Any] = {}
        self._pending_block: Optional[Dict[str, Any]] = None
        self._step_ewma_s = 0.0   # per-scan-step device time estimate
        # -- dispatch/sync accounting (tokens_per_dispatch telemetry and
        #    the host-syncs-per-request regression guard read these) ------
        self.decode_dispatches = 0   # device decode dispatches (any K)
        self.decode_host_syncs = 0   # blocking device->host decode fetches
        self.prefill_dispatches = 0  # prefill passes (one per prompt fill;
        #                              stays 0 under the ragged plan —
        #                              prompts ride mixed rounds instead)
        #: dispatches through the ragged kernel family: every mixed
        #: round, plus plain/spec dispatches whose attention ran the
        #: pallas ragged kernel (use_kernel)
        self.ragged_dispatches = 0
        #: per-dispatch-kind counts (the ragged plan's three descriptor
        #: kinds): "decode" = plain K-blocks and single ticks, "verify"
        #: = speculative draft+verify blocks, "mixed" = ragged mixed
        #: prefill+decode rounds
        self.dispatch_kinds: Dict[str, int] = {"decode": 0, "verify": 0,
                                               "mixed": 0}
        if prefill_flash is None:
            # auto: pallas flash attention for the FULL-PROMPT forward on
            # TPU (O(T*block) VMEM instead of a dense (T, T) score
            # materialization).  Scope: the start==0 un-chunked prefill
            # only — chunked prefills and prefix-cache tails run
            # paged_extend's gather attention, which has no flash analog
            # here.  Probed once at a representative geometry; any
            # unprobed per-bucket Mosaic rejection at runtime degrades to
            # the dense prefill (see _do_prefill), never kills serving.
            from tpulab.tpu.platform import is_tpu
            prefill_flash = is_tpu() and _flash_compiles(
                d_model // n_heads, compute_dtype, self.pool.device)
        self.prefill_flash = bool(prefill_flash)
        self._prefill_kw = dict(n_heads=n_heads, n_layers=n_layers,
                                compute_dtype=compute_dtype,
                                n_kv_heads=n_kv, rope_theta=rope_theta)
        self._prefill = self._build_prefill(self.prefill_flash)
        # tail/chunk prefill against existing pool context (prefix-cache
        # hits, chunked long prompts) — compiled per tail-length bucket
        self._extend = self._jit(
            partial(paged_extend, n_heads=n_heads, n_layers=n_layers,
                    compute_dtype=compute_dtype, n_kv_heads=n_kv,
                    rope_theta=rope_theta),
            (1,), (psh, kvsh, rep, rep, rep, rep), (rep, kvsh))
        # -- speculative decoding (a draft model riding the SAME pool
        #    through a second per-lane page table; docs/PERFORMANCE.md) -----
        # ``draft_params`` arms it: the draft proposes K tokens per lane
        # inside the fused dispatch, the target verifies all of them in one
        # batched forward, and each dispatch emits up to K+1 ACCEPTED
        # tokens — multiplying the decode-block dispatch amortization by
        # the acceptance rate.  Emitted tokens are bit-identical to the
        # non-speculative stream (greedy and device-sampled); host-sampled
        # lanes never enter the speculative path, and a lane whose rolling
        # acceptance EWMA falls below ``spec_accept_floor`` (or whose
        # verify dispatch trips chaos) degrades to plain blocks for the
        # rest of its request.
        self._spec: Optional[Dict[str, Any]] = None
        self.spec_accept_floor = float(spec_accept_floor)
        self.spec_dispatches = 0        # speculative decode dispatches
        self.spec_fallbacks = 0         # lanes degraded to plain blocks
        self.spec_draft_prefills = 0    # draft-table warm-up forwards
        self.spec_tokens_drafted = 0    # proposals verified by the target
        self.spec_tokens_accepted = 0   # of those, emitted (accepted)
        self.spec_probes = 0            # probe blocks re-trying a degraded
        #                                 lane (EWMA degrades only)
        self.spec_probe_recoveries = 0  # probes whose lane stayed
        #                                 speculative (acceptance came back)
        self._spec_block_cache: Dict[int, Any] = {}
        if draft_params is not None:
            dl = draft_n_layers or n_layers
            dh = draft_n_heads or n_heads
            dkv = draft_n_kv_heads or (n_kv if draft_n_heads is None else dh)
            dd = weight_shape(draft_params["layer0"]["wqkv"])[0]
            if dd // dh != d_model // n_heads or dkv != n_kv:
                raise ValueError(
                    "draft model KV geometry (head_dim, n_kv_heads) must "
                    "match the target's — both write the shared paged pool")
            if dl > n_layers:
                raise ValueError("draft_n_layers must be <= n_layers (the "
                                 "draft shares the pool's layer axis)")
            if self.mesh is not None:
                from tpulab.parallel.sharding import \
                    transformer_param_shardings
                self._draft_param_sh = transformer_param_shardings(
                    draft_params, self.mesh)
                draft_dev = jax.device_put(draft_params,
                                           self._draft_param_sh)
            else:
                self._draft_param_sh = None
                draft_dev = jax.device_put(draft_params, self.pool.device)
            self._spec = {"params": draft_dev,
                          "n_heads": dh, "n_layers": dl, "n_kv_heads": dkv}
            self._spec_kw = dict(n_heads=n_heads, n_layers=n_layers,
                                 draft_n_heads=dh, draft_n_layers=dl,
                                 compute_dtype=compute_dtype,
                                 n_kv_heads=n_kv, draft_n_kv_heads=dkv,
                                 rope_theta=rope_theta,
                                 use_kernel=self.use_kernel,
                                 mesh=self.mesh)
            # draft-table warm-up: one fused draft forward over whatever
            # context tail the second table is missing (never synced)
            self._draft_extend = self._jit(
                partial(paged_extend, n_heads=dh, n_layers=dl,
                        compute_dtype=compute_dtype, n_kv_heads=dkv,
                        rope_theta=rope_theta),
                (1,), (self._draft_param_sh, kvsh, rep, rep, rep, rep),
                (rep, kvsh))
        self.prefix_cache = PrefixCache(self.pool) if prefix_cache else None
        # host-memory KV tier (tpulab.kvcache): None/False = off (zero
        # cost); True = a manager with the default host budget; an int =
        # budget bytes; a KVOffloadManager = bring-your-own (shared
        # store/transfer).  When on, preemption swaps KV device->host and
        # resume swaps back (no re-prefill), and prefix-cache eviction
        # demotes to / promotes from the host tier.
        self._owns_offload = False
        if kv_offload is None or kv_offload is False:
            self.kv_offload = None
        else:
            from tpulab.kvcache import (DEFAULT_HOST_BUDGET,
                                        KVOffloadManager)
            if isinstance(kv_offload, KVOffloadManager):
                self.kv_offload = kv_offload
            else:
                budget = (DEFAULT_HOST_BUDGET if kv_offload is True
                          else int(kv_offload))
                self.kv_offload = KVOffloadManager(self.pool, budget)
                self._owns_offload = True
        if self.kv_offload is not None and self.prefix_cache is not None:
            self.prefix_cache.on_evict = self._demote_prefix
            self.prefix_cache.promote_fn = self._promote_prefix
        # fleet KV fabric publish (tpulab.kvfabric, docs/SERVING.md
        # "Fleet KV fabric"): finished FIRST prefills export their
        # prompt-only KV to the host tier under ("fab", content_digest) —
        # the same write-behind swap_out preemption uses — plus the
        # prefill's last-position logits row under ("fablog", digest), so
        # a FetchKV RPC can serve both to the digest's routed-astray
        # fetchers without evicting this replica's own copy.  Requires
        # kv_offload (the host tier IS the export buffer).  Publishes
        # ride the legacy prefill dispatch only: the ragged plan's mixed
        # rounds never fetch a host-visible logits row (documented
        # limitation; ROADMAP follow-up).
        if kv_publish and self.kv_offload is None:
            raise ValueError("kv_publish requires kv_offload")
        self.kv_publish = bool(kv_publish)
        from collections import OrderedDict as _OD
        self._fab_handles: "Dict[bytes, Any]" = _OD()
        self._fab_lock = threading.Lock()
        self.kv_publishes = 0  # prompt snapshots exported to the fabric
        #: rolling prefill throughput (tokens/s, EWMA) — the fabric's
        #: cost gate weighs a remote fetch's wire time against simply
        #: recomputing the prompt here (0.0 until the first prefill)
        self.prefill_ewma_tok_s = 0.0
        if prefill_chunk is not None:
            if prefill_chunk < page_size:
                raise ValueError("prefill_chunk must be >= page_size")
            # chunk starts must stay page-aligned (a chunk's successor
            # writes from a page boundary)
            prefill_chunk -= prefill_chunk % page_size
        self.prefill_chunk = prefill_chunk
        #: optional tpulab.utils.tracing.ChromeTraceRecorder — the batcher
        #: records queue/prefill/decode-chunk spans per request (spans ride
        #: per-lane rows; the serving layer may attach one post-hoc)
        self.trace = trace
        #: optional tpulab.utils.metrics.GenerationMetrics — TTFT /
        #: inter-token / queue-wait / e2e distributions observed per
        #: completed request at the source, not polled
        self.metrics = metrics
        #: optional tpulab.obs.FlightRecorder — per-request wide events
        #: (docs/OBSERVABILITY.md "Flight recorder").  Armed, each request
        #: carries a small detail dict (block sizes, ITL samples, swap
        #: events, peak pages) and completion attaches the summary to the
        #: future as ``_tpulab_flight``; requests whose wide event the RPC
        #: layer assembles (flight_owner="rpc") are never double-recorded.
        #: None = disarmed: one None check per site, tokens unchanged
        #: either way (the recorder observes, never steers).
        self.flight = flight
        #: debugz on-demand XLA profiler capture (arm_profile): dict with
        #: remaining/dir/active, managed by the scheduler thread only
        self._profile: Optional[Dict[str, Any]] = None
        self._queue: List[_PagedRequest] = []
        self._requests: Dict[Future, _PagedRequest] = {}
        self._active: List[Optional[_PagedRequest]] = [None] * lanes
        self._admit_counter = 0
        self.preemptions = 0
        #: of those, evictions of BATCH-class lanes (the offline lane is
        #: the first preemption victim by design — a high number here
        #: with few online preemptions means the lane is doing its job)
        self.batch_preemptions = 0
        if self.hbm is not None:
            # register as the KV tenant AFTER kv_offload is settled (the
            # reclaimable estimate reads it) and claim the page store's
            # tracked bytes — the ledger now mirrors the allocator gauge
            from tpulab.hbm import KV_TENANT
            self.hbm.register(KV_TENANT, reclaim=self._hbm_reclaim,
                              reclaimable=self._hbm_reclaimable,
                              gauge=lambda: self.pool.hbm_bytes)
            self.hbm.mirror_claim(KV_TENANT, "pool", self.pool.hbm_bytes)
        self.completed_requests = 0  # futures resolved successfully
        self.tokens_generated = 0    # emitted across all requests
        self._cv = threading.Condition()
        self._shutdown = False
        self._thread = threading.Thread(target=self._run, name="cbatch",
                                        daemon=True)
        self._thread.start()

    def _jit(self, fn, donate, in_sh, out_sh):
        """``jax.jit`` with explicit in/out shardings under a mesh — the
        partitioner then inserts the collectives (psum after row-parallel
        matmuls, gathers where layouts demand) INSIDE the compiled
        program — and a plain single-device jit otherwise (``in_sh`` /
        ``out_sh`` ignored; mesh=None is exactly the pre-mesh build).

        Jitted programs are shared through a process-level memo
        (:data:`_JIT_MEMO`) keyed by the function + its baked static
        config + donation + shardings: engines with identical program
        geometry (test suites, fleets of loopback replicas, bench
        modes) reuse one compiled-program cache instead of re-tracing
        and re-compiling identical HLO per engine.  Params and pools
        are traced ARGUMENTS, never baked, so sharing is purely a
        compile-time dedupe; configs with unhashable baked state (e.g.
        a flash-attention closure) fall back to a private jit.

        With an arbiter measuring scratch, the (shared) jit is wrapped
        per engine so each distinct shape signature records its
        compile-time temp bytes as a ``("scratch", ...)`` ledger claim
        (tpulab.hbm.scratch) — the third tenant the pre-arbiter
        headroom math never saw."""
        import jax

        def build():
            if self.mesh is None:
                return jax.jit(fn, donate_argnums=donate)
            return jax.jit(fn, donate_argnums=donate,
                           in_shardings=in_sh, out_shardings=out_sh)

        base = getattr(fn, "func", fn)
        try:
            key = (base.__module__, base.__qualname__,
                   getattr(fn, "args", ()),
                   tuple(sorted(getattr(fn, "keywords", {}).items())),
                   donate,
                   in_sh if self.mesh is not None else None,
                   out_sh if self.mesh is not None else None)
            hash(key)
        except TypeError:
            key = None
        if key is None:
            jitted = build()
        else:
            with _JIT_MEMO_LOCK:
                jitted = _JIT_MEMO.get(key)
            if jitted is None:
                jitted = build()
                with _JIT_MEMO_LOCK:
                    jitted = _JIT_MEMO.setdefault(key, jitted)
        if self.hbm is not None and self.hbm.measure_scratch:
            from tpulab.hbm import MeasuredJit
            name = getattr(getattr(fn, "func", fn), "__name__", "jit")
            jitted = MeasuredJit(jitted, self.hbm, name)
        return jitted

    def _build_prefill(self, flash: bool):
        """Jitted fused prefill, compiled per prompt-length bucket (powers
        of two); ``flash`` selects the pallas prompt-attention kernel."""
        attn_fn = None
        if flash:
            from tpulab.ops.flash_attention import make_flash_attention_fn
            attn_fn = make_flash_attention_fn(causal=True)
        rep, kvsh = self._rep, self.pool.kv_sharding
        return self._jit(
            partial(paged_prefill, attention_fn=attn_fn,
                    **self._prefill_kw),
            (1,), (self._param_sh, kvsh, rep, rep, rep), (rep, kvsh))

    # -- public -------------------------------------------------------------
    def submit(self, prompt, steps: int, on_token=None,
               sampling: Optional[SamplingParams] = None,
               priority: int = 0, stop_tokens=None,
               logprobs: bool = False, deadline=None,
               trace_id: Optional[str] = None,
               export_digest: Optional[bytes] = None,
               tenant: Optional[str] = None,
               flight_owner: Optional[str] = None,
               request_class: str = "online") -> Future:
        """``on_token(token, index)`` (optional) streams tokens as they
        decode — the hook the Generate RPC rides for paged serving.
        ``sampling`` selects the token policy (default greedy).
        ``logprobs=True`` resolves the future to ``(tokens, logprobs)``
        (each token's chosen log-probability, computed on device) instead
        of the plain token list, and ``on_token`` is then called with a
        third ``logprob`` argument.
        ``stop_tokens`` (iterable of token ids, e.g. the tokenizer's EOS)
        ends generation early: the stop token is emitted as the final
        token and the lane/pages free at that tick.
        ``priority`` orders admission (higher first; FIFO within a class)
        and arms preemption: a queued request strictly outranking an active
        one evicts it — the victim's pages free immediately and it resumes
        later by re-prefilling prompt+generated (exact-token resume; with a
        prefix cache the recompute mostly hits cached pages).
        ``deadline`` (a :class:`~tpulab.core.deadline.Deadline` or a float
        budget in seconds) bounds the request: the scheduler cancels it
        before its next step once expired — lane and KV pages free within
        one tick — and the future fails with DeadlineExceeded.
        ``trace_id`` tags this request's queue/prefill/decode spans in the
        attached ``trace`` recorder (the Generate RPC threads the client's
        id through here, merging both processes into one timeline).
        ``export_digest`` (requires ``kv_offload``) demotes the finished
        request's KV to the host tier under ``("ship", digest)`` at lane
        release — the prefill-replica half of disaggregated serving
        (tpulab.disagg): submit with ``steps=1`` and the resulting
        snapshot covers exactly the prompt; the export
        :class:`~tpulab.kvcache.offload.SwapHandle` lands on the future
        as ``_tpulab_kv_export`` (None when the swap degraded).
        ``tenant`` tags the request for flight-recorder / debugz
        attribution (never read by the scheduler); ``flight_owner="rpc"``
        marks the wide event as assembled by the RPC layer — the engine
        still attaches its completion summary to the future
        (``_tpulab_flight``) but does not record it itself.
        ``request_class`` ("online" default, or "batch" — the offline
        batch lane, docs/SERVING.md) ranks the request: a batch request
        queues behind EVERY online request regardless of priority, is
        the first preemption victim when an online arrival needs its
        lane or pages, and its ``on_token`` hook (a checkpoint sink,
        not an interactive consumer) never drags the fused-decode block
        size down."""
        if request_class not in ("online", "", "batch"):
            raise ValueError(f"unknown request_class {request_class!r} "
                             "(want 'online' or 'batch')")
        flat = np.asarray(prompt).reshape(-1)
        if isinstance(deadline, Deadline):
            deadline = deadline.expiry
        elif deadline is not None:
            deadline = _time.monotonic() + float(deadline)
        n_prompt = len(flat)
        if n_prompt == 0:
            raise ValueError("empty prompt")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if n_prompt + steps > self.max_len:
            raise ValueError(f"prompt+steps exceeds max_len {self.max_len}")
        if flat.min() < 0 or flat.max() >= self.vocab:
            # XLA gather CLAMPS out-of-bounds ids — silent garbage tokens;
            # reject at the host boundary instead
            raise ValueError(f"prompt token ids outside [0, {self.vocab})")
        if export_digest is not None and self.kv_offload is None:
            raise ValueError("export_digest requires kv_offload")
        req = _PagedRequest(prompt, steps, on_token=on_token,
                            sampling=sampling, priority=priority,
                            stop_tokens=stop_tokens, logprobs=logprobs,
                            deadline=deadline, trace_id=trace_id,
                            tenant=tenant,
                            batch=request_class == "batch")
        req.export_digest = export_digest
        if self.flight is not None or flight_owner:
            self._fl_arm(req, flight_owner)
        with self._cv:
            if self._shutdown:
                raise RuntimeError("ContinuousBatcher is shut down")
            self._enqueue_locked(req, front_of_class=False)
            self._requests[req.future] = req
            self._cv.notify()
        return req.future

    def submit_shipped(self, prompt, steps: int, first_token: int,
                       handle, on_token=None,
                       sampling: Optional[SamplingParams] = None,
                       priority: int = 0, stop_tokens=None, deadline=None,
                       trace_id: Optional[str] = None,
                       tenant: Optional[str] = None,
                       flight_owner: Optional[str] = None) -> Future:
        """Admit a request whose prompt KV arrived SHIPPED from a prefill
        replica (tpulab.disagg) — the decode-replica half of
        disaggregated serving.

        ``handle`` is the resident host-tier snapshot a
        :class:`~tpulab.disagg.KVShipper` import minted (None = shipment
        lost: the request still admits and prefills locally), and
        ``first_token`` the prefill replica's index-0 pick — emitted to
        ``on_token`` here (index 0) so the stream the consumer sees is
        identical to a unified replica's.  Admission promotes the
        snapshot through the existing ``KVOffloadManager.restore`` path:
        the lane starts decoding with ZERO prefill dispatches.  Every
        degraded shipment (lost, corrupt, chaos-tripped, budget-refused,
        restore failure) falls back to the exact local prefill — which
        recomputes the same KV, so token parity holds either way.

        Host-sampled requests (``temperature > 0`` without device
        sampling) are rejected: their PRNG stream is keyed by draw
        order, which does not survive the replica hop; greedy and
        device-sampled streams are keyed by (seed, position) and do."""
        flat = np.asarray(prompt).reshape(-1)
        if isinstance(deadline, Deadline):
            deadline = deadline.expiry
        elif deadline is not None:
            deadline = _time.monotonic() + float(deadline)
        n_prompt = len(flat)
        if n_prompt == 0:
            raise ValueError("empty prompt")
        if steps < 1:
            raise ValueError("steps must be >= 1")
        if n_prompt + steps > self.max_len:
            raise ValueError(f"prompt+steps exceeds max_len {self.max_len}")
        if flat.min() < 0 or flat.max() >= self.vocab:
            raise ValueError(f"prompt token ids outside [0, {self.vocab})")
        if not 0 <= int(first_token) < self.vocab:
            raise ValueError(
                f"shipped first token outside [0, {self.vocab})")
        sp = sampling or SamplingParams()
        if sp.temperature > 0.0 and not sp.device:
            raise ValueError(
                "shipped-KV admission requires greedy or device sampling "
                "(host-side PRNG streams do not survive the replica hop)")
        if handle is not None and self.kv_offload is None:
            raise ValueError("shipped-KV admission requires kv_offload")
        if handle is not None and handle.length != n_prompt:
            raise ValueError(
                f"shipment covers {handle.length} positions, prompt has "
                f"{n_prompt}")
        req = _PagedRequest(prompt, steps, on_token=on_token,
                            sampling=sp, priority=priority,
                            stop_tokens=stop_tokens, deadline=deadline,
                            trace_id=trace_id, tenant=tenant)
        if self.flight is not None or flight_owner:
            self._fl_arm(req, flight_owner)
        # the first-token pick already happened on the prefill replica:
        # seed the lane as a resume (a degraded restore then re-prefills
        # and DISCARDS its logits, exactly like a preemption resume)
        req.tokens_out.append(int(first_token))
        req.kv_handle = handle
        req.resumed = True
        self.tokens_generated += 1
        self._emit(req, int(first_token), 0, None)
        if req.finished():  # steps == 1 or first token hit a stop token
            if handle is not None and self.kv_offload is not None:
                self.kv_offload.discard(handle)
            req.kv_handle = None
            self._flight_complete(req)
            req.future.set_result(self._result_of(req))
            self.completed_requests += 1
            return req.future
        with self._cv:
            if self._shutdown:
                raise RuntimeError("ContinuousBatcher is shut down")
            self._enqueue_locked(req, front_of_class=False)
            self._requests[req.future] = req
            self._cv.notify()
        return req.future

    def cancel(self, future: Future) -> None:
        """Abort a submitted request (freed at the next tick boundary)."""
        with self._cv:
            req = self._requests.get(future)
            if req is not None:
                req.cancelled = True
                if req in self._queue:  # never started: finish immediately
                    self._queue.remove(req)
                    self._requests.pop(future, None)
                    self._discard_handle(req)
        if req is not None and req not in self._active and not future.done():
            future.cancel()

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify()
        self._thread.join(timeout=30)
        if not self._thread.is_alive() and self.prefix_cache is not None:
            self.prefix_cache.on_evict = None  # shutdown clear != pressure
            self.prefix_cache.clear()  # release the cache's page refs
        if self._owns_offload and not self._thread.is_alive():
            self.kv_offload.close()  # drain write-behind, free host tier
        if self._owns_pool and not self._thread.is_alive():
            self.pool.close()  # free the page stores' HBM eagerly
            if self.hbm is not None:
                from tpulab.hbm import KV_TENANT
                self.hbm.release(KV_TENANT, "pool")

    @property
    def active_lanes(self) -> int:
        with self._cv:
            return sum(r is not None for r in self._active)

    @property
    def queued_requests(self) -> int:
        with self._cv:
            return len(self._queue)

    @property
    def spec_acceptance(self) -> float:
        """Lifetime draft acceptance rate (accepted / drafted)."""
        return self.spec_tokens_accepted / max(1, self.spec_tokens_drafted)

    @property
    def admission_cost_factor(self) -> float:
        """Cost multiplier the admission frontend applies to this
        engine's requests (serving/admission.py).  A speculative request
        holds a SECOND page table (the draft KV) next to the target's
        and burns draft+verify compute on rejected proposals —
        drafted-but-rejected tokens are not free, so cost-aware
        admission must not plan capacity as if they were."""
        return 2.0 if self._spec is not None else 1.0

    # -- telemetry (no-ops without an attached recorder/metrics) ------------
    def _span(self, name: str, lane: int, t0: float, dur: float,
              req: _PagedRequest, **extra) -> None:
        """One request-lifecycle span on the lane's trace row."""
        tr = self.trace
        if tr is None:
            return
        if req.trace_id:
            extra["trace_id"] = req.trace_id
        tr.add_span(name, t0, dur, tid=lane, lane=lane, **extra)

    def _flush_decode_chunk(self, req: _PagedRequest, lane: int,
                            now: float, **extra) -> None:
        """Close the open decode-chunk span at ``now`` and start the next
        (K>1 dispatch passes ``block=K`` — block-sized decode spans)."""
        n = len(req.tokens_out)
        if req.chunk_t0 is not None and n > req.chunk_start:
            self._span("decode", lane, req.chunk_t0, now - req.chunk_t0,
                       req, first=req.chunk_start,
                       tokens=n - req.chunk_start, **extra)
        req.chunk_t0 = now
        req.chunk_start = n

    def _note_complete(self, req: _PagedRequest) -> None:
        if self.metrics is not None:
            self.metrics.observe_e2e(_time.perf_counter() - req.t_submit)

    # -- flight recorder (tpulab.obs, docs/OBSERVABILITY.md) ----------------
    #: per-request detail lists stay bounded — a pathological request
    #: must not turn its own wide event into a memory leak
    FLIGHT_DETAIL_CAP = 1024

    @staticmethod
    def _fl_arm(req: _PagedRequest, owner: Optional[str]) -> None:
        """Attach the per-request flight detail dict (armed path only)."""
        req.fl = {"owner": owner, "blocks": [], "itl": [],
                  "swap_outs": 0, "swap_ins": 0, "preempts": 0,
                  "pages_peak": 0, "chaos0": chaos.fired_snapshot()}

    def _fl_block(self, req: _PagedRequest, k: int, n: int,
                  dt: Optional[float]) -> None:
        """One fused-decode dispatch's contribution to the wide event:
        block size K, tokens emitted, the spread per-token latency."""
        fl = req.fl
        if fl is None:
            return
        if len(fl["blocks"]) < self.FLIGHT_DETAIL_CAP:
            fl["blocks"].append((k, n))
        if dt is not None and len(fl["itl"]) < self.FLIGHT_DETAIL_CAP:
            fl["itl"].append((dt, n))
        pages = len(req.pages) + len(req.draft_pages)
        if pages > fl["pages_peak"]:
            fl["pages_peak"] = pages

    def _fl_pages(self, req: _PagedRequest) -> None:
        fl = req.fl
        if fl is not None:
            pages = len(req.pages) + len(req.draft_pages)
            if pages > fl["pages_peak"]:
                fl["pages_peak"] = pages

    def _flight_summary(self, req: _PagedRequest,
                        outcome: str) -> Dict[str, Any]:
        """The engine's half of the wide event (the RPC layer adds
        admission/status/transport fields for requests it owns)."""
        now = _time.perf_counter()
        ev: Dict[str, Any] = {
            "kind": "paged", "outcome": outcome, "tenant": req.tenant,
            "request_class": "batch" if req.batch else "online",
            "priority": req.priority, "trace_id": req.trace_id,
            "prompt_tokens": int(len(req.prompt)), "steps": req.steps,
            "tokens": len(req.tokens_out),
            "t_submit": req.t_submit, "t_prefill0": req.t_prefill0,
            "t_first": req.t_first, "t_last": req.t_last,
            "e2e_s": now - req.t_submit, "lane": req.lane,
            "pages": len(req.pages),
        }
        if req.t_prefill0 is not None:
            ev["queue_wait_s"] = req.t_prefill0 - req.t_submit
        if req.t_first is not None:
            ev["ttft_s"] = req.t_first - req.t_submit
        if req.spec_drafted:
            ev["spec_drafted"] = req.spec_drafted
            ev["spec_accepted"] = req.spec_accepted
            ev["spec_acceptance"] = round(
                req.spec_accepted / req.spec_drafted, 4)
        fl = req.fl
        if fl is not None:
            ev["pages_peak"] = max(fl["pages_peak"], len(req.pages))
            ev["block_ks"] = [k for k, _n in fl["blocks"]]
            ev["preempts"] = fl["preempts"]
            ev["swap_outs"] = fl["swap_outs"]
            ev["swap_ins"] = fl["swap_ins"]
            if fl["itl"]:
                itl = np.repeat([d for d, _ in fl["itl"]],
                                [n for _, n in fl["itl"]])
                ev["itl_ms"] = {
                    "p50": round(float(np.percentile(itl, 50)) * 1e3, 4),
                    "p99": round(float(np.percentile(itl, 99)) * 1e3, 4),
                    "max": round(float(itl.max()) * 1e3, 4),
                    "n": int(itl.size)}
            trips = {}
            for point, n in chaos.fired_snapshot().items():
                d = n - fl["chaos0"].get(point, 0)
                if d > 0:
                    trips[point] = d
            if trips:
                ev["chaos_trips"] = trips
        if self.hbm is not None:
            ev["hbm_pressure_events"] = self.hbm.pressure_events
        return ev

    def _flight_complete(self, req: _PagedRequest,
                         outcome: str = "SUCCESS") -> None:
        """Completion hook (every future-resolution site): attach the
        engine summary to the future BEFORE it resolves (race-free, the
        ``_tpulab_compute_s`` idiom) and record it — unless the RPC layer
        owns this request's wide event."""
        fr = self.flight
        if fr is None and req.fl is None:
            return
        ev = self._flight_summary(req, outcome)
        req.future._tpulab_flight = ev
        owner = req.fl.get("owner") if req.fl is not None else None
        if fr is not None and owner != "rpc":
            fr.observe(ev)

    # -- debugz (tpulab.obs.debugz) -----------------------------------------
    def arm_profile(self, ticks: int, log_dir: Optional[str] = None) -> str:
        """Arm ``jax.profiler`` around the next ``ticks`` scheduler ticks
        (the Debug RPC's ``profile_ticks``).  The capture starts at the
        next pass the scheduler runs and stops after ``ticks`` passes;
        returns the trace directory (``tensorboard --logdir`` it)."""
        if int(ticks) < 1:
            raise ValueError("profile_ticks must be >= 1")
        if log_dir is None:
            import tempfile
            log_dir = tempfile.mkdtemp(prefix="tpulab-profile-")
        with self._cv:
            if self._profile is not None:
                raise RuntimeError("a profiler capture is already armed")
            self._profile = {"remaining": int(ticks), "dir": log_dir,
                             "active": False}
            self._cv.notify()
        return log_dir

    def _profile_step(self, done: bool = False) -> None:
        """Scheduler-thread profiler bookkeeping: start the armed capture,
        count one pass, stop at zero (or at shutdown with ``done``)."""
        prof = self._profile
        if prof is None:
            return
        import jax
        if done:
            if prof["active"]:
                jax.profiler.stop_trace()
            self._profile = None
            return
        if not prof["active"]:
            jax.profiler.start_trace(prof["dir"])
            prof["active"] = True
            return  # the NEXT ticks are captured; arming pass is free
        prof["remaining"] -= 1
        if prof["remaining"] <= 0:
            jax.profiler.stop_trace()
            self._profile = None

    def debug_state(self) -> Dict[str, Any]:
        """Live scheduler introspection for debugz (one consistent
        snapshot under the scheduler lock): lanes, queue, elastic pool +
        ladder position, dispatch counters, speculative and prefix-cache
        state."""
        now = _time.perf_counter()
        with self._cv:
            lanes = []
            for lane, req in enumerate(self._active):
                if req is None:
                    lanes.append({"lane": lane, "state": "idle"})
                    continue
                lanes.append({
                    "lane": lane,
                    "state": ("prefill" if req.pending_prompt
                              else "decode"),
                    "request_class": "batch" if req.batch else "online",
                    "tenant": req.tenant, "priority": req.priority,
                    "trace_id": req.trace_id,
                    "age_s": round(now - req.t_submit, 6),
                    "tokens": len(req.tokens_out), "steps": req.steps,
                    "prompt_tokens": int(len(req.prompt)),
                    "pages": len(req.pages),
                    "draft_pages": len(req.draft_pages),
                    "cancelled": req.cancelled,
                })
            queue_head = [{"tenant": q.tenant, "priority": q.priority,
                           "age_s": round(now - q.t_submit, 6),
                           "prompt_tokens": int(len(q.prompt)),
                           "steps": q.steps}
                          for q in self._queue[:16]]
            queued = len(self._queue)
            profile_armed = self._profile is not None
        pool = self.pool
        rung, size = 0, self._hbm_pool_base
        while size and size * 2 <= pool.n_pages:
            size *= 2
            rung += 1
        out: Dict[str, Any] = {
            "kind": "paged",
            "lanes": lanes,
            "queued_requests": queued,
            "queue_head": queue_head,
            "pool": {"n_pages": pool.n_pages,
                     "free_pages": pool.free_pages,
                     "page_size": pool.page_size,
                     "page_nbytes": pool.page_nbytes,
                     "hbm_bytes": pool.hbm_bytes,
                     "n_shards": pool.n_shards,
                     "elastic": self.hbm is not None,
                     "ladder_base": self._hbm_pool_base,
                     "ladder_rung": rung,
                     "grows": self.hbm_grows,
                     "shrinks": self.hbm_shrinks},
            "dispatch": {"decode_block": self.decode_block,
                         "decode_dispatches": self.decode_dispatches,
                         "decode_host_syncs": self.decode_host_syncs,
                         "prefill_dispatches": self.prefill_dispatches,
                         "ragged": self.ragged,
                         "use_kernel": self.use_kernel,
                         "ragged_dispatches": self.ragged_dispatches,
                         "kinds": dict(self.dispatch_kinds),
                         "preemptions": self.preemptions,
                         "batch_preemptions": self.batch_preemptions,
                         "completed_requests": self.completed_requests,
                         "tokens_generated": self.tokens_generated},
            "profile_armed": profile_armed,
        }
        if self._spec is not None:
            out["spec"] = {"dispatches": self.spec_dispatches,
                           "fallbacks": self.spec_fallbacks,
                           "tokens_drafted": self.spec_tokens_drafted,
                           "tokens_accepted": self.spec_tokens_accepted,
                           "acceptance": round(self.spec_acceptance, 4),
                           "probes": self.spec_probes,
                           "probe_recoveries": self.spec_probe_recoveries}
        pc = self.prefix_cache
        if pc is not None:
            out["prefix_cache"] = {"entries": len(pc), "hits": pc.hits,
                                   "misses": pc.misses,
                                   "host_promotions": pc.host_promotions}
        return out

    # -- scheduler ----------------------------------------------------------
    @staticmethod
    def _rank(req: _PagedRequest):
        """Scheduling rank: ``(class, priority)`` — every online request
        outranks every batch request (the offline lane sits strictly
        below online traffic at ANY priority); within a class, priority
        orders as before."""
        return (0 if req.batch else 1, req.priority)

    def _enqueue_locked(self, req: _PagedRequest,
                        front_of_class: bool) -> None:
        """Insert by rank (online before batch, higher priority first,
        FIFO within a class); ``front_of_class`` puts the request ahead
        of its equals (preempted victims resume before new same-priority
        arrivals)."""
        rank = self._rank(req)
        i = 0
        for i, q in enumerate(self._queue):
            if (self._rank(q) < rank
                    or (front_of_class and self._rank(q) == rank)):
                self._queue.insert(i, req)
                return
        self._queue.append(req)

    def _alloc_page(self) -> Optional[int]:
        """Pool page, evicting cold prefix-cache entries under pressure —
        live requests always outrank cached prefixes (with kv_offload the
        eviction DEMOTES the entry to the host tier instead of losing it)."""
        page = self.pool.allocate_page()
        while (page is None and self.prefix_cache is not None
               and self.prefix_cache.evict_for_alloc()):
            page = self.pool.allocate_page()
        return page

    # -- host KV tier (kv_offload) -------------------------------------------
    def _demote_prefix(self, digest: bytes, page: int) -> None:
        """PrefixCache.on_evict hook: spill the evicted page host-side."""
        self.kv_offload.demote(digest, page, self.pool.kv)

    def _promote_prefix(self, digest: bytes) -> Optional[int]:
        """PrefixCache.promote_fn hook: resurrect a demoted entry into a
        fresh pool page (plain allocate — promotion must not evict OTHER
        device entries and thrash the cache against itself)."""
        mgr = self.kv_offload
        if not mgr.has_prefix(digest):
            return None
        page = self.pool.allocate_page()
        if page is None:
            return None
        new_kv = mgr.promote(digest, page, self.pool.kv)
        if new_kv is None:
            self.pool.release_pages([page])
            return None
        self.pool.kv = new_kv
        return page

    # -- HBM economy (tpulab.hbm): the KV tenant --------------------------
    #: bound on how long a blocking grow request waits for a write-behind
    #: model eviction to land (only paid when every lane is starved —
    #: the scheduler had nothing else to do anyway)
    HBM_GROW_TIMEOUT_S = 0.5

    def _page_nbytes(self) -> int:
        return max(1, self.pool.page_nbytes)

    def _hbm_ladder_down(self, total: int) -> int:
        """Largest ladder size (base * 2^k) <= ``total`` (base floor)."""
        size = self._hbm_pool_base
        while size * 2 <= total:
            size *= 2
        return size

    def _hbm_reclaimable(self) -> int:
        """Non-mutating estimate of the KV bytes pressure could free:
        pages already contiguously free at the top of the store, plus
        idle prefix-cache pages, plus live-but-idle lane KV the host
        tier could absorb (demotion needs ``kv_offload`` — without the
        tier a preempted lane re-prefills, which frees pages but burns
        recompute, so it is not advertised as cheap headroom)."""
        pages = self.pool.shrinkable_pages()
        if self.prefix_cache is not None:
            pages += len(self.prefix_cache)
        if self.kv_offload is not None:
            with self._cv:
                lane_pages = sum(len(r.pages) for r in self._active
                                 if r is not None)
            pages = pages + min(lane_pages,
                                self.kv_offload.headroom_pages())
        return pages * self._page_nbytes()

    def _hbm_reclaim(self, nbytes: int) -> int:
        """Arbiter pressure hook (foreign thread): record the target and
        wake the scheduler — demotion/preemption/shrink run at the next
        tick boundary, where no dispatched block is in flight.  Returns
        the bytes this tenant expects to free (its progress promise)."""
        est = min(int(nbytes), self._hbm_reclaimable())
        if est <= 0:
            return 0
        with self._cv:
            self._hbm_reclaim_bytes = max(self._hbm_reclaim_bytes,
                                          int(nbytes))
            self._cv.notify()
        return est

    def _service_hbm_locked(self) -> None:
        """Serve an outstanding arbiter reclaim at the tick boundary:
        demote idle prefix-cache KV to the host tier, preempt
        live-but-idle lanes (their KV swaps out through the existing
        preemption path — the resumed stream is bit-exact), then shrink
        the page store's top and release the bytes to the ledger.  Only
        runs with no dispatched-ahead block in flight, so no in-flight
        decode page is ever victimized."""
        need = self._hbm_reclaim_bytes
        if not need or self.hbm is None or self._pending_block is not None:
            return
        from tpulab.hbm import KV_TENANT
        pn = self._page_nbytes()
        target = (need + pn - 1) // pn
        # snap the post-shrink total onto the size ladder (bounded
        # compiled shapes): free at least the target, landing on the
        # largest ladder size at or below what remains
        target = max(target, self.pool.n_pages
                     - self._hbm_ladder_down(
                         max(1, self.pool.n_pages - target)))
        # 1) idle KV first: cold prefix-cache entries demote for free
        while (self.pool.shrinkable_pages() < target
               and self.prefix_cache is not None
               and self.prefix_cache.evict_for_alloc()):
            pass
        # 2) live-but-idle lanes: preempt coldest-priority, least-progress
        # first — with kv_offload their KV demotes to the host tier and
        # the resume is recompute-free; without it the resume re-prefills
        # (the pre-arbiter preemption contract either way)
        while self.pool.shrinkable_pages() < target:
            victims = [(req.priority, -req.admit_seq, lane)
                       for lane, req in enumerate(self._active)
                       if req is not None]
            if not victims:
                break
            _, _, lane = min(victims)
            self._preempt_locked(lane)
            self.hbm_demotions += 1
        dropped = self.pool.shrink(target)
        self._hbm_reclaim_bytes = 0
        if dropped:
            self.hbm_shrinks += 1
            self.hbm.mirror_claim(KV_TENANT, "pool", self.pool.hbm_bytes)

    def _hbm_break_hoard_locked(self) -> None:
        """Preempt the most recently admitted lane when every lane is
        starved with nothing free — the hold-and-wait breaker for the
        elastic regime (see the _run call site).  The victim resumes
        exactly (preemption contract); progress resumes immediately."""
        if self.pool.free_pages > 0:
            return
        active = [(req.admit_seq, lane)
                  for lane, req in enumerate(self._active)
                  if req is not None and req.pages]
        if len(active) < 2:
            return  # one holder is not a hold-and-wait cycle
        _, lane = max(active)
        self._preempt_locked(lane)
        self.hbm_demotions += 1
        # the starvation streak stays up until a tick makes real
        # progress: admission is suppressed meanwhile (_admit_locked), so
        # the victim cannot re-admit and re-form the cycle before the
        # surviving holders finish

    def _hbm_maybe_grow(self, block: bool) -> bool:
        """Per-tick grow probe (scheduler thread, no locks held): when
        queued or starved requests want more pages than the pool holds,
        ask the arbiter for the bytes — the pressure protocol may evict
        a cold model to supply them.  ``block=True`` (every lane starved:
        nothing else to do) waits briefly for write-behind evictions to
        land; probes are free and retried next tick otherwise."""
        if self.hbm is None:
            return False
        with self._cv:
            if self._hbm_reclaim_bytes or self._pending_block is not None:
                return False  # being squeezed (or a block in flight)
            ps = self.page_size
            want = 0
            for req in self._queue[:self.lanes]:
                if req.kv_handle is not None:
                    want += req.kv_handle.n_pages + 1
                else:
                    t = len(req.pending_prompt) or (len(req.prompt)
                                                    + len(req.tokens_out))
                    want += (t + req.steps - len(req.tokens_out)
                             + ps - 1) // ps + 1
            for req in self._active:
                if req is None:
                    continue
                if req.pending_prompt:  # starved prefill / pending resume
                    want += max(0, (len(req.pending_prompt) + ps - 1) // ps
                                + 1 - len(req.pages))
                else:  # decoding: pages its remaining appends will write
                    need = (req.length + req.steps - len(req.tokens_out)
                            + ps - 1) // ps
                    want += max(0, need - len(req.pages))
            deficit = want - self.pool.free_pages
        if deficit <= 0:
            return False
        from tpulab.hbm import KV_TENANT
        pn = self._page_nbytes()
        # ask only for what the economy could plausibly supply (free
        # headroom + what pressure could evict) — an oversized request
        # would deny forever instead of growing incrementally — and snap
        # the new total onto the size ladder (bounded compiled shapes):
        # the smallest ladder size covering the demand we can afford,
        # else the largest affordable step toward it
        avail = (max(0, self.hbm.free_hbm_bytes)
                 + self.hbm.reclaimable_bytes(exclude=KV_TENANT))
        n = self.pool.n_pages
        affordable = n + avail // pn  # a rung may cost more than the
        #                               deficit — affordability is what
        #                               the economy could supply, period
        target = self._hbm_pool_base
        while target < n + deficit and target * 2 <= affordable:
            target *= 2
        pages = target - n
        if pages <= 0:
            return False  # static-budget degrade: queue on today's pool
        granted = self.hbm.request(
            KV_TENANT, ("pool", "grow"), pages * pn,
            timeout=self.HBM_GROW_TIMEOUT_S if block else 0.0,
            probe=not block)
        if not granted:
            return False
        with self._cv:
            if self._pending_block is None:
                self.pool.grow(pages)
                self.hbm_grows += 1
            # consolidate: fold the grant into the pool claim (mirror
            # first so the total never dips below the tracked bytes)
            self.hbm.mirror_claim(KV_TENANT, "pool", self.pool.hbm_bytes)
            self.hbm.release(KV_TENANT, ("pool", "grow"))
            self._cv.notify()
        return True

    def _admit_to_lane_locked(self, lane: int) -> bool:
        """Admit the queue head into a free lane (needs at least one page
        to start); False when the pool can't supply it."""
        page = self._alloc_page()
        if page is None:
            return False
        req = self._queue.pop(0)
        req.pages.append(page)
        req.admit_seq = self._admit_counter
        self._admit_counter += 1
        req.lane = lane
        self._active[lane] = req
        return True

    def _admit_locked(self) -> None:
        # elastic-regime hold-and-wait breaker (tpulab.hbm): while the
        # scheduler is in a starvation streak WITH live page-holders,
        # feed the pages freed by _hbm_break_hoard_locked to those
        # holders instead of re-admitting — the preempted victim
        # re-enters once decoding progresses.  With no holders at all
        # (e.g. right after an arbiter squeeze emptied every lane),
        # admission must proceed or nothing ever runs again.
        if not (self.hbm is not None and self._hbm_starved_passes >= 2
                and any(r is not None for r in self._active)):
            for lane in range(self.lanes):
                if self._active[lane] is None and self._queue:
                    if not self._admit_to_lane_locked(lane):
                        break
        # preemption: while the queue head strictly outranks the weakest
        # active request (rank = (class, priority): BATCH lanes are the
        # first victims — any online arrival evicts batch work before
        # touching another online lane; within a class the priority
        # tie-break stays most-recently-admitted falls first — least
        # progress lost), evict it and admit the head.  Zero-page lanes
        # (page-starved prefills) are skipped: evicting them frees
        # nothing and they already yield every tick.
        while self._queue:
            head = self._queue[0]
            head_rank = self._rank(head)
            # a victim only helps if releasing it can actually free a page:
            # skip lanes whose every page is prefix-cache-shared
            # (refcount > 1) — preempting them loses decode progress for
            # zero freed pages
            victims = [(self._rank(req) + (-req.admit_seq, lane))
                       for lane, req in enumerate(self._active)
                       if req is not None and self._rank(req) < head_rank
                       and any(self.pool.refcount(p) == 1
                               for p in req.pages)]
            if not victims:
                return
            lane = min(victims)[-1]
            self._preempt_locked(lane)
            if not self._admit_to_lane_locked(lane):
                # Defensive: the victim filter above requires at least one
                # refcount==1 page, so every preemption frees >=1 page and
                # a one-page admit succeeds under the current filter.  Kept
                # as a guard for future filter changes (e.g. admitting
                # multi-page heads) — the head retries next scheduling pass.
                return

    def _preempt_locked(self, lane: int) -> None:
        """Evict the lane's request: free its pages now, re-queue it for an
        exact-token resume (re-prefill of prompt+generated; no sampling
        PRNG draws are consumed on resume, so seeded sequences are
        unchanged by preemption).  With ``kv_offload`` the lane's live KV
        pages are first snapshotted device->host (async write-behind —
        only the gather dispatch happens here); the resume then swaps
        them back in with zero prefill dispatches, and the re-prefill
        below becomes the FALLBACK for a failed/dropped swap."""
        req = self._active[lane]
        self._fl_pages(req)
        if req.fl is not None:
            req.fl["preempts"] += 1
        # a mid-prompt ragged lane (length > 0 with chunks still pending)
        # is never snapshotted: its partial-prompt KV does not match the
        # resume length contract below — the resume re-prefills exactly
        if (self.kv_offload is not None and req.length > 0
                and not req.pending_prompt):
            t_sw0 = _time.perf_counter()
            needed = (req.length + self.page_size - 1) // self.page_size
            req.kv_handle = self.kv_offload.swap_out(
                req.pages[:needed], req.length, self.pool.kv)
            if req.kv_handle is not None:
                if req.fl is not None:
                    req.fl["swap_outs"] += 1
                self._span("swap_out", lane, t_sw0,
                           _time.perf_counter() - t_sw0, req,
                           pages=needed, tokens=req.length)
        self.pool.release_pages(req.pages)
        req.pages = []
        # the draft table is never snapshotted: it is cheap to regenerate
        # (one draft forward at resume), so its pages go home NOW and the
        # resume's warm-up rebuilds it exactly
        if req.draft_pages:
            self.pool.release_pages(req.draft_pages)
            req.draft_pages = []
        req.draft_len = 0
        if req.tokens_out:
            # feed everything but the last emitted token; the resume
            # prefill's logits are discarded (that pick already happened)
            req.pending_prompt = (list(req.prompt)
                                  + list(req.tokens_out[:-1]))
            req.resumed = True
        else:
            req.pending_prompt = list(req.prompt)
        req.length = 0
        req.pf_started = False   # ragged plan: the resume re-secures pages
        self._active[lane] = None
        self._enqueue_locked(req, front_of_class=True)
        self.preemptions += 1
        if req.batch:
            self.batch_preemptions += 1

    def _run(self) -> None:
        import jax.numpy as jnp
        while True:
            with self._cv:
                while (not self._shutdown and not self._queue
                       and not any(self._active)
                       and not self._hbm_reclaim_bytes):
                    self._cv.wait()
                if self._shutdown and not self._queue and not any(self._active):
                    self._profile_step(done=True)  # close an open capture
                    return
                # HBM arbiter pressure: serve an outstanding reclaim at
                # the tick boundary (no dispatched block is in flight
                # here — dispatch-ahead is suppressed while a reclaim is
                # pending, so in-flight decode pages are never victims)
                self._service_hbm_locked()
                # cancellation + deadline sweep: unconditional, so cancels
                # and expiries land even when no lane can make progress
                # (page-starved prefills).  Expired requests free their
                # lane and pages HERE — before the next step runs
                swept = []
                expired = []
                now = _time.monotonic()
                for lane, req in enumerate(self._active):
                    if req is None:
                        continue
                    if req.cancelled:
                        self._release_lane_locked(lane, req)
                        swept.append(req)
                    elif req.deadline is not None and now >= req.deadline:
                        self._release_lane_locked(lane, req)
                        expired.append(req)
                if self._queue:  # queued requests expire in place
                    still = []
                    for req in self._queue:
                        if (req.deadline is not None
                                and now >= req.deadline):
                            self._requests.pop(req.future, None)
                            self._discard_handle(req)
                            expired.append(req)
                        else:
                            still.append(req)
                    self._queue[:] = still
                self._admit_locked()
                snapshot = list(self._active)
            self._profile_step()  # debugz on-demand capture bookkeeping
            for req in swept:
                self._flight_complete(req, "CANCELLED")
                if not req.future.done():
                    req.future.cancel() or req.future.set_exception(
                        RuntimeError("generation cancelled"))
            for req in expired:
                if self.metrics is not None:
                    self.metrics.note_deadline_expired()
                self._flight_complete(req, "DEADLINE_EXCEEDED")
                if not req.future.done():
                    req.future.set_exception(DeadlineExceeded(
                        "generation deadline exceeded "
                        f"({len(req.tokens_out)}/{req.steps} tokens)"))
            try:
                prefilled = False
                if self.ragged:
                    # ragged dispatch plan: pending prompts and decode
                    # lanes advance together in ONE fused mixed round
                    prefilled = self._ragged_round(snapshot, jnp)
                else:
                    for lane, req in enumerate(snapshot):
                        if req is not None and req.pending_prompt:
                            prefilled |= self._do_prefill(req, jnp, lane)
                if prefilled:
                    # a steps==1 request can complete at prefill
                    done_reqs = []
                    with self._cv:
                        for lane, req in enumerate(self._active):
                            if (req is not None and not req.pending_prompt
                                    and req.finished()):
                                self._release_lane_locked(lane, req)
                                done_reqs.append(req)
                        self._admit_locked()
                        snapshot = list(self._active)
                    for req in done_reqs:
                        if not req.future.done():
                            self._flight_complete(req)
                            req.future.set_result(self._result_of(req))
                            self.completed_requests += 1
                            self._note_complete(req)
                progressed = self._tick(snapshot, jnp) or prefilled
                if self.hbm is not None:
                    # KV-burst side of the economy: queued/starved demand
                    # asks the arbiter for pool bytes (a cold model may be
                    # evicted to supply them); a cheap probe per tick,
                    # blocking only when every lane is starved anyway
                    self._hbm_maybe_grow(block=not progressed)
                if not progressed:
                    if self.hbm is not None:
                        # elastic-regime hold-and-wait breaker: lanes are
                        # sized for the GROWN pool, so a denied grow can
                        # strand N partial page-holders where the static
                        # world (lanes sized to the fixed pool) never
                        # could.  After two fully-starved passes with
                        # nothing free, preempt the newest lane (exact
                        # resume) so the eldest can finish — degraded
                        # throughput, never a livelock.
                        self._hbm_starved_passes += 1
                        if self._hbm_starved_passes >= 2:
                            with self._cv:
                                self._hbm_break_hoard_locked()
                    # every lane starved (pool pressure): back off instead
                    # of hot-spinning until pages free up
                    with self._cv:
                        self._cv.wait(timeout=0.01)
                else:
                    self._hbm_starved_passes = 0
            except Exception as e:  # noqa: BLE001 - fail active requests
                # a dispatched-ahead block died with the pool: its device
                # arrays and lane mapping are meaningless after recovery
                self._pending_block = None
                with self._cv:
                    for lane, req in enumerate(self._active):
                        if req is not None:
                            if not req.future.done():
                                self._flight_complete(req, "INTERNAL")
                                req.future.set_exception(e)
                            self._requests.pop(req.future, None)
                            self._active[lane] = None
                # donated pools may be gone after a failed step — rebuild
                if self.prefix_cache is not None:
                    self.prefix_cache.drop_all()  # entries died with the pool
                self.pool.reset()

    def _do_prefill(self, req: _PagedRequest, jnp, lane: int = 0) -> bool:
        """Fused prompt prefill: one compiled forward (per length bucket)
        fills the whole prompt's KV pages.  With a prefix cache, shared
        full-page prefixes are reused and only the tail runs (paged_extend);
        with ``prefill_chunk`` long tails run in page-aligned chunks.
        Returns False (retry later) when the pool can't yet supply the
        prompt's pages."""
        if req.cancelled or req.length != 0:  # swept / already started
            return False
        t = len(req.pending_prompt)
        if req.kv_handle is not None:
            # recompute-free resume: swap the preemption snapshot back in
            # instead of re-prefilling.  True = restored (zero prefill
            # dispatches); False = page-starved (handle kept, retry next
            # pass); None = swap degraded (handle consumed, fall through
            # to the exact re-prefill below — today's path)
            swapped = self._try_swap_in(req, t, lane)
            if swapped is not None:
                return swapped
        prompt = np.asarray(req.pending_prompt, np.int32)
        shared: List[int] = []
        digests: List[bytes] = []
        if self.prefix_cache is not None:
            shared, digests = self.prefix_cache.lookup(prompt, self.page_size)
        # page layout: shared prefix pages first, then private pages (the
        # admission page + extras) for the tail/write region
        private = req.pages
        req.pages = shared + private
        needed = (t + self.page_size - 1) // self.page_size
        while len(req.pages) < needed:
            page = self._alloc_page()
            if page is None:
                # page pressure: release partial holdings before retrying —
                # two starved prefills must not hold-and-wait each other
                self.pool.release_pages(req.pages)
                req.pages = []
                return False
            req.pages.append(page)
        start = len(shared) * self.page_size
        tables = np.zeros((self.max_pages,), np.int32)
        tables[:len(req.pages)] = req.pages
        tables_j = jnp.asarray(tables)
        # pages secured: the queue wait ends HERE (first prefill only — a
        # preemption resume re-prefills but already left the queue once)
        t_pf0 = _time.perf_counter()
        if req.t_prefill0 is None:
            req.t_prefill0 = t_pf0
            self._span("queue_wait", lane, req.t_submit,
                       t_pf0 - req.t_submit, req)
            if self.metrics is not None:
                self.metrics.observe_queue_wait(t_pf0 - req.t_submit)
        # chaos: prefill fault site — an error here rides the scheduler's
        # recovery path (fail actives + pool reset), a delay is a slow
        # prefill under deadline pressure
        chaos.trip("engine.prefill")
        self.prefill_dispatches += 1
        if start == 0 and (self.prefill_chunk is None
                           or t <= self.prefill_chunk):
            t_pad = 1 << (t - 1).bit_length()  # pow2 bucket: small jit cache
            tokens = np.zeros((1, t_pad), np.int32)
            tokens[0, :t] = prompt
            try:
                last_logits, self.pool.kv = self._prefill(
                    self.params, self.pool.kv, tables_j,
                    jnp.asarray(tokens), jnp.int32(t))
            except Exception:
                # the one-geometry probe can't cover every pow2 bucket: a
                # per-bucket Mosaic rejection (compile-time, so the donated
                # pool is untouched) degrades this batcher to the dense
                # prefill instead of failing requests.  An EXECUTION-time
                # failure has already consumed the donated pool — re-raise
                # to the scheduler's recovery path (fail actives + pool
                # reset) rather than retrying against a deleted buffer.
                if (not self.prefill_flash
                        or getattr(self.pool.kv, "is_deleted",
                                   lambda: False)()):
                    raise
                import logging
                logging.getLogger("tpulab.engine").warning(
                    "flash prefill failed at bucket %d; degrading this "
                    "batcher to dense prefill", t_pad, exc_info=True)
                self.prefill_flash = False
                self._prefill = self._build_prefill(False)
                last_logits, self.pool.kv = self._prefill(
                    self.params, self.pool.kv, tables_j,
                    jnp.asarray(tokens), jnp.int32(t))
        else:
            # tail (and/or chunked) prefill against resident context
            chunk = self.prefill_chunk or (t - start)
            last_logits = None
            while start < t:
                m = min(chunk, t - start)
                m_pad = 1 << (m - 1).bit_length()
                tokens = np.zeros((1, m_pad), np.int32)
                tokens[0, :m] = prompt[start:start + m]
                last_logits, self.pool.kv = self._extend(
                    self.params, self.pool.kv, tables_j,
                    jnp.asarray(tokens), jnp.int32(start),
                    jnp.int32(start + m))
                start += m
        req.length = t
        req.pending_prompt = []
        self._fl_pages(req)
        was_resumed = req.resumed
        if was_resumed:
            # preemption resume: the fed tail ends at tokens_out[-2]; the
            # last emitted token was picked before eviction — discard these
            # logits, consume no PRNG state, just continue decoding
            req.resumed = False
        else:
            sp = req.sampling
            if sp.device and sp.temperature > 0.0:
                # first token rides the SAME (seed, position) stream as the
                # decode ticks (position t-1 = the last prompt token's
                # query; decode ticks start at position t) — one request is
                # one reproducible stream end to end.  The prefill logits
                # row is fetched once per request; per-TICK logits are
                # never fetched for device-sampled lanes.
                import jax.numpy as _j
                tok = int(np.asarray(_device_sample_token(
                    _j.asarray(last_logits, _j.float32),
                    _j.float32(sp.temperature),
                    _j.asarray([sp.seed & 0xFFFFFFFF,
                                (sp.seed >> 32) & 0xFFFFFFFF], _j.uint32),
                    _j.int32(t - 1))))
            else:
                tok = sp.pick(np.asarray(last_logits))
            req.tokens_out.append(tok)
            self.tokens_generated += 1
            lp = None
            if req.want_logprobs:
                # same f32 device log_softmax as paged_decode_step: one
                # request's logprob stream is one precision end to end
                import jax as _jax
                import jax.numpy as _j
                lp = float(np.asarray(_jax.nn.log_softmax(
                    _j.asarray(last_logits, _j.float32))[tok]))
                req.logprobs_out.append(lp)
            self._emit(req, tok, 0, lp)
        # prefill span closes after the first-token pick (the pick's logits
        # fetch is the fence that makes the device time real); decode
        # chunks start from here
        t_pf1 = _time.perf_counter()
        self._span("prefill", lane, t_pf0, t_pf1 - t_pf0, req,
                   prompt_tokens=t, cached_pages=len(shared))
        req.chunk_t0 = t_pf1
        req.chunk_start = len(req.tokens_out)
        if not was_resumed:
            req.t_first = t_pf1
            req.t_last = t_pf1
            if self.metrics is not None:
                self.metrics.observe_ttft(t_pf1 - req.t_submit)
        if self.prefix_cache is not None and not was_resumed:
            # count each logical request once (resume prefills re-walk
            # already-counted pages) and publish only first-prefill pages:
            # full prompt pages are immutable from here on (decode writes
            # at positions >= t), while a resume's tail pages hold
            # generated tokens unique to this request — not worth caching
            self.prefix_cache.count_lookup(len(shared), len(digests))
            self.prefix_cache.insert(digests, req.pages[:len(digests)])
        dt = t_pf1 - t_pf0
        if dt > 0:
            # rolling prefill throughput — the fabric cost gate's
            # recompute-time estimate (see kv_publish in __init__)
            inst = t / dt
            self.prefill_ewma_tok_s = (
                inst if self.prefill_ewma_tok_s == 0.0
                else 0.7 * self.prefill_ewma_tok_s + 0.3 * inst)
        if self.kv_publish and not was_resumed and req.export_digest is None:
            self._fab_publish(req, prompt, t, last_logits)
        return True

    #: published fabric snapshots kept addressable (digest -> handle);
    #: beyond this the oldest export is forgotten — its store entries
    #: removed — so the fabric can never squat the whole host tier
    FAB_PUBLISH_CAP = 32

    def _fab_publish(self, req: _PagedRequest, prompt: np.ndarray, t: int,
                     last_logits) -> None:
        """Export a finished first prefill to the fleet KV fabric
        (tpulab.kvfabric): the prompt's pages snapshot to the host tier
        under ``("fab", digest)`` through the same write-behind swap_out
        the preemption path uses (gather dispatched HERE, before any
        decode write into the tail page, so dispatch ordering makes the
        snapshot prompt-only), and the last-position logits row lands
        beside it under ``("fablog", digest)`` so a fetcher picks the
        first token under its OWN sampling seed.  Best-effort end to
        end: a degraded swap, a budget-refused put or a mid-flight
        eviction all surface as an honest FetchKV NOT_FOUND — never a
        wrong answer."""
        from tpulab.disagg.wire import prompt_digest
        digest = prompt_digest(prompt)
        with self._fab_lock:
            if digest in self._fab_handles:
                self._fab_handles.move_to_end(digest)
                return
        n_pages = (t + self.page_size - 1) // self.page_size
        handle = self.kv_offload.swap_out(
            req.pages[:n_pages], t, self.pool.kv, key=("fab", digest))
        if handle is None:
            return
        if not self.kv_offload.store.put(
                ("fablog", digest),
                np.asarray(last_logits, np.float32).reshape(-1)):
            self.kv_offload.discard(handle)
            return
        self.kv_publishes += 1
        with self._fab_lock:
            self._fab_handles[digest] = handle
            self._fab_handles.move_to_end(digest)
            while len(self._fab_handles) > self.FAB_PUBLISH_CAP:
                old_dig, old_h = self._fab_handles.popitem(last=False)
                self.kv_offload.discard(old_h)
                self.kv_offload.store.remove(("fablog", old_dig))

    def fab_handle(self, digest: bytes):
        """The published fabric snapshot for ``digest`` (a resident or
        still-in-flight :class:`~tpulab.kvcache.offload.SwapHandle`), or
        None — the FetchKV server's lookup.  Thread-safe: the RPC thread
        reads while the scheduler publishes/evicts.  A hit bumps the
        publish-registry LRU (fabric-popular digests stay addressable)
        WITHOUT touching the host store's own recency — the store read
        goes through ``peek``."""
        with self._fab_lock:
            h = self._fab_handles.get(digest)
            if h is not None:
                self._fab_handles.move_to_end(digest)
            return h

    def _try_swap_in(self, req: _PagedRequest, t: int,
                     lane: int) -> Optional[bool]:
        """Restore a preempted lane's host-tier KV snapshot into freshly
        allocated pages (see _do_prefill for the tri-state contract).
        ``t`` is the resume length — by construction equal to the
        snapshot's covered positions (prompt + generated - 1)."""
        handle = req.kv_handle
        needed = handle.n_pages
        while len(req.pages) < needed:
            page = self._alloc_page()
            if page is None:
                # page pressure: release partial holdings (no hold-and-
                # wait), KEEP the handle — the snapshot outlives retries
                self.pool.release_pages(req.pages)
                req.pages = []
                return False
            req.pages.append(page)
        t0 = _time.perf_counter()
        new_kv = self.kv_offload.restore(handle, req.pages[:needed],
                                         self.pool.kv)
        req.kv_handle = None
        if new_kv is None:
            # degraded swap: hand the pages back and run the normal
            # re-prefill (which re-does prefix lookup and its own page
            # accounting from a clean slate)
            self.pool.release_pages(req.pages)
            req.pages = []
            return None
        self.pool.kv = new_kv
        req.length = t
        req.pending_prompt = []
        req.resumed = False  # the first-token pick happened pre-preemption
        now = _time.perf_counter()
        if req.fl is not None:
            req.fl["swap_ins"] += 1
        self._fl_pages(req)
        self._span("swap_in", lane, t0, now - t0, req,
                   pages=needed, tokens=t)
        req.chunk_t0 = now        # decode chunks restart here
        req.chunk_start = len(req.tokens_out)
        return True

    # -- ragged dispatch plan (mixed prefill+decode rounds) ------------------
    #: max prefill tokens one mixed round carries per lane (the pow2
    #: segment-width bucket ceiling; ``prefill_chunk`` lowers it) —
    #: longer prompts take multiple rounds, decode lanes never stalling
    #: behind them
    RAGGED_CHUNK_CAP = 256

    def _ragged_prefill_start(self, req: _PagedRequest, lane: int) -> bool:
        """Host half of a prefill under the ragged plan: prefix-cache
        lookup + secure EVERY page the full prompt needs (all-or-nothing,
        the legacy _do_prefill contract — two starved prefills must not
        hold-and-wait each other), then mark the lane chunk-ready.
        True = segments may build; False = page-starved (retry later)."""
        prompt = np.asarray(req.pending_prompt, np.int32)
        t = len(prompt)
        shared: List[int] = []
        digests: List[bytes] = []
        if self.prefix_cache is not None:
            shared, digests = self.prefix_cache.lookup(prompt,
                                                       self.page_size)
        private = req.pages
        req.pages = shared + private
        needed = (t + self.page_size - 1) // self.page_size
        while len(req.pages) < needed:
            page = self._alloc_page()
            if page is None:
                self.pool.release_pages(req.pages)
                req.pages = []
                return False
            req.pages.append(page)
        # shared prefix positions are already resident: chunks cover
        # only the tail (the last prompt token is never served shared)
        req.pf_digests = digests
        req.pf_shared = len(shared)
        req.length = len(shared) * self.page_size
        del req.pending_prompt[:req.length]
        req.pf_started = True
        req.pf_t0 = _time.perf_counter()
        if req.t_prefill0 is None:
            req.t_prefill0 = req.pf_t0
            self._span("queue_wait", lane, req.t_submit,
                       req.pf_t0 - req.t_submit, req)
            if self.metrics is not None:
                self.metrics.observe_queue_wait(req.pf_t0 - req.t_submit)
        # chaos: same prefill fault site + semantics as _do_prefill (one
        # trip per prefill start, errors ride the scheduler's recovery)
        chaos.trip("engine.prefill")
        return True

    def _ragged_round(self, snapshot, jnp) -> bool:
        """One fused ragged mixed round (the unified dispatch plan):
        every prefilling lane advances by one prompt chunk and — with no
        dispatched-ahead block in flight — every decoding lane advances
        by one token, all through ONE ``paged_mixed_step`` dispatch over
        per-lane ``(q_len, kv_len)`` segments.  Lanes finishing their
        prompt emit their first token from the same dispatch (no
        separate prefill program, no per-lane logits fetch).  With no
        pending prompts this is a no-op and the K-block decode path
        owns the tick.  Returns True when any lane made progress."""
        progressed = False
        segs: List = []                     # (lane, req)
        for lane, req in enumerate(snapshot):
            if req is None or not req.pending_prompt or req.cancelled:
                continue
            if req.kv_handle is not None:
                swapped = self._try_swap_in(req, len(req.pending_prompt),
                                            lane)
                if swapped is True:
                    progressed = True
                    continue
                if swapped is False:
                    continue         # page-starved: snapshot kept
            if not req.pf_started and not self._ragged_prefill_start(
                    req, lane):
                continue             # page-starved: retry next pass
            segs.append((lane, req))
        if not segs:
            return progressed
        # decode lanes join the round only when no dispatched-ahead
        # block is in flight (its device carry covers those lanes)
        decode_parts: List = []
        if self._pending_block is None:
            for lane, req in enumerate(snapshot):
                if (req is None or req.pending_prompt or req.cancelled
                        or not req.tokens_out):
                    continue
                need = req.length // self.page_size + 1
                new: List[int] = []
                while len(req.pages) < need:
                    page = self._alloc_page()
                    if page is None:
                        break
                    req.pages.append(page)
                    new.append(page)
                if len(req.pages) < need:
                    for _ in new:    # starved: return the partial take
                        self.pool.release_pages([req.pages.pop()])
                    continue
                decode_parts.append((lane, req))
        cap = min(self.prefill_chunk or self.RAGGED_CHUNK_CAP,
                  self.RAGGED_CHUNK_CAP)
        chunks: Dict[int, int] = {}
        m_max = 1
        for lane, req in segs:
            c = min(len(req.pending_prompt), cap)
            chunks[lane] = c
            m_max = max(m_max, c)
        m_pad = 1 << (m_max - 1).bit_length()   # pow2 bucket: small jits
        b = self.lanes
        tables = np.zeros((b, self.max_pages), np.int32)
        seq = np.zeros((b, m_pad), np.int32)
        q_lens = np.zeros((b,), np.int32)
        kv_lens = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b, 2), np.uint32)
        host_lanes: List[int] = []
        lane_reqs: Dict[int, _PagedRequest] = {}
        for lane, req in segs:
            c = chunks[lane]
            lane_reqs[lane] = req
            seq[lane, :c] = req.pending_prompt[:c]
            q_lens[lane] = c
            kv_lens[lane] = req.length + c
            tables[lane, :len(req.pages)] = req.pages
            sp = req.sampling
            if c == len(req.pending_prompt) and not req.resumed \
                    and sp.temperature > 0.0:
                # final chunk: this round's pick IS the first token
                if sp.device:
                    temps[lane] = sp.temperature
                    seeds[lane] = (sp.seed & 0xFFFFFFFF,
                                   (sp.seed >> 32) & 0xFFFFFFFF)
                else:
                    host_lanes.append(lane)
        for lane, req in decode_parts:
            lane_reqs[lane] = req
            seq[lane, 0] = req.tokens_out[-1]
            q_lens[lane] = 1
            kv_lens[lane] = req.length + 1
            tables[lane, :len(req.pages)] = req.pages
            sp = req.sampling
            if sp.temperature > 0.0:
                if sp.device:
                    temps[lane] = sp.temperature
                    seeds[lane] = (sp.seed & 0xFFFFFFFF,
                                   (sp.seed >> 32) & 0xFFFFFFFF)
                else:
                    host_lanes.append(lane)
        if decode_parts:
            # decode lanes advance one tick this round — same fault site
            chaos.trip("engine.step")
        t0 = _time.perf_counter()
        nt_dev, lp_dev, last_dev, self.pool.kv = self._mixed(
            self.params, self.pool.kv, jnp.asarray(tables),
            jnp.asarray(seq), jnp.asarray(q_lens), jnp.asarray(kv_lens),
            jnp.asarray(temps), jnp.asarray(seeds))
        self.decode_dispatches += 1
        self._note_dispatch("mixed")
        next_tokens = np.asarray(nt_dev, np.int32).copy()
        logprobs_arr = np.asarray(lp_dev, np.float32).copy()
        self.decode_host_syncs += 1
        if host_lanes:
            # fetch ONLY the host-sampled rows (same shape discipline —
            # and PRNG rule — as _tick_single)
            rows = np.asarray(
                last_dev[jnp.asarray(np.asarray(host_lanes, np.int32))])
            self.decode_host_syncs += 1
            for i, lane in enumerate(host_lanes):
                req = lane_reqs[lane]
                next_tokens[lane] = req.sampling.pick(rows[i])
                if req.want_logprobs:
                    row = rows[i].astype(np.float32)
                    row = row - row.max()
                    logprobs_arr[lane] = float(
                        row[next_tokens[lane]]
                        - np.log(np.exp(row).sum()))
        now = _time.perf_counter()
        self._step_ewma_s = (0.8 * self._step_ewma_s + 0.2 * (now - t0)
                             if self._step_ewma_s else now - t0)
        emits: List = []
        completed: List = []
        with self._cv:
            for lane, req in segs:
                if self._active[lane] is not req or req.cancelled:
                    continue
                c = chunks[lane]
                req.length += c
                del req.pending_prompt[:c]
                self._fl_pages(req)
                progressed = True
                if req.pending_prompt:
                    continue         # mid-prompt: nothing emitted yet
                t_total = req.length
                was_resumed = req.resumed
                if was_resumed:
                    # the pick already happened before preemption/on the
                    # prefill replica: discard this round's (stateless)
                    # sample, just continue decoding
                    req.resumed = False
                else:
                    tok = int(next_tokens[lane])
                    req.tokens_out.append(tok)
                    self.tokens_generated += 1
                    lp = None
                    if req.want_logprobs:
                        lp = float(logprobs_arr[lane])
                        req.logprobs_out.append(lp)
                    emits.append((req, tok, len(req.tokens_out) - 1, lp))
                self._span("prefill", lane, req.pf_t0, now - req.pf_t0,
                           req, prompt_tokens=t_total,
                           cached_pages=req.pf_shared)
                req.chunk_t0 = now
                req.chunk_start = len(req.tokens_out)
                if not was_resumed:
                    req.t_first = now
                    req.t_last = now
                    if self.metrics is not None:
                        self.metrics.observe_ttft(now - req.t_submit)
                if self.prefix_cache is not None and not was_resumed:
                    self.prefix_cache.count_lookup(req.pf_shared,
                                                   len(req.pf_digests))
                    self.prefix_cache.insert(
                        req.pf_digests, req.pages[:len(req.pf_digests)])
                req.pf_started = False
            for lane, req in decode_parts:
                if self._active[lane] is not req or req.cancelled:
                    continue
                self._probe_countdown_locked(req)
                req.length += 1
                tok = int(next_tokens[lane])
                req.tokens_out.append(tok)
                self.tokens_generated += 1
                progressed = True
                dt = (now - req.t_last) if req.t_last is not None else None
                if self.metrics is not None and dt is not None:
                    self.metrics.observe_itl(dt)
                self._fl_block(req, 1, 1, dt)
                req.t_last = now
                lp = None
                if req.want_logprobs:
                    lp = float(logprobs_arr[lane])
                    req.logprobs_out.append(lp)
                emits.append((req, tok, len(req.tokens_out) - 1, lp))
                done = req.finished()
                if (done or len(req.tokens_out) - req.chunk_start
                        >= self.TRACE_DECODE_CHUNK):
                    self._flush_decode_chunk(req, lane, now)
                if done:
                    self._release_lane_locked(lane, req)
                    completed.append(req)
            self._admit_locked()
        # user callbacks and future resolution OUTSIDE the scheduler lock
        for req, tok, i, lp in emits:
            self._emit(req, tok, i, lp)
        for req in completed:
            if not req.future.done():
                self._flight_complete(req)
                req.future.set_result(self._result_of(req))
                self.completed_requests += 1
                self._note_complete(req)
        return progressed or bool(segs)

    def _discard_handle(self, req: _PagedRequest) -> None:
        """Drop a never-to-be-restored snapshot (cancel/expiry while
        queued) so it stops holding host-tier budget."""
        if req.kv_handle is not None:
            if self.kv_offload is not None:
                self.kv_offload.discard(req.kv_handle)
            req.kv_handle = None

    @staticmethod
    def _emit(req: _PagedRequest, token: int, index: int,
              logprob: Optional[float] = None) -> None:
        """Explicit hook contract: ``on_token(tok, i)`` normally;
        ``on_token(tok, i, logprob)`` iff the request asked for
        ``logprobs=True`` (no signature sniffing — a 3-arg call on a
        2-arg hook with ``logprobs=True`` is a caller bug and raises)."""
        if req.on_token is not None:
            try:
                if req.want_logprobs:
                    req.on_token(token, index, logprob)
                else:
                    req.on_token(token, index)
            except Exception:  # pragma: no cover - consumer hook
                import logging
                logging.getLogger("tpulab.engine").exception(
                    "on_token hook failed")

    def _note_dispatch(self, kind: str) -> None:
        """Dispatch-kind accounting (the ragged plan's three descriptor
        kinds); ``ragged_dispatches`` counts the ragged kernel family —
        every mixed round, plus decode/verify dispatches whose attention
        ran the pallas ragged kernel."""
        self.dispatch_kinds[kind] += 1
        if kind == "mixed" or self.use_kernel:
            self.ragged_dispatches += 1

    # -- fused decode dispatch ----------------------------------------------
    def _block_fn(self, k: int):
        """Jitted K-step fused decode (compiled once per block size)."""
        fn = self._block_cache.get(k)
        if fn is None:
            rep, kvsh = self._rep, self.pool.kv_sharding
            fn = self._jit(partial(paged_decode_block, k=k, **self._step_kw),
                           (1,), (self._param_sh, kvsh) + (rep,) * 8,
                           (rep,) * 7 + (kvsh,))
            self._block_cache[k] = fn
        return fn

    def _tight_slack_s(self) -> float:
        """Deadline slack below which a lane counts as *tight* (adaptive K
        drops to <=2): roughly two max-size blocks of measured decode
        time, clamped to a sane band while the EWMA warms up."""
        est = self._step_ewma_s or 0.005
        return min(1.0, max(0.05, 2.0 * self.decode_block * est))

    def _pick_block_k(self, decode_lanes) -> int:
        """Adaptive fused-decode block size for this dispatch.

        - any host-sampled (``top_k``/``top_p``) lane -> 1: its per-token
          pick needs the logits row on host every tick;
        - any deadline-tight lane -> <=2: the sweep acts at block
          boundaries, so a big block would overshoot the deadline;
        - a streaming consumer with NO queue pressure -> <=2: keep ITL
          smooth when latency is what the caller is buying;
        - otherwise (throughput pressure, batch-style ``.result()``
          consumers) the full ``decode_block`` ceiling;
        - never longer than the largest remaining step budget needs
          (covering it with one block instead of trailing short blocks).
        """
        kmax = self.decode_block
        if kmax <= 1:
            return 1
        now = _time.monotonic()
        want = kmax
        streaming = False
        max_rem = 1
        for _lane, req in decode_lanes:
            sp = req.sampling
            if sp.temperature > 0.0 and not sp.device:
                return 1
            if (req.deadline is not None
                    and req.deadline - now < self._tight_slack_s()):
                want = min(want, 2)
            if req.on_token is not None and not req.batch:
                # batch lanes run throughput-optimized: their on_token
                # hook is a durable checkpoint sink, not an interactive
                # consumer — never let it drag the whole block to K<=2
                streaming = True
            max_rem = max(max_rem, req.steps - len(req.tokens_out))
        if streaming and not self._queue:
            want = min(want, 2)
        cover = next((m for m in self.BLOCK_K_MENU if m >= max_rem),
                     self.BLOCK_K_MENU[-1])
        k = min(want, cover)
        return max(m for m in self.BLOCK_K_MENU if m <= k)

    def _reserve_block_pages(self, decode_lanes, k: int):
        """Pre-allocate every page the next K appends will write, per lane.

        Decode step j writes K/V at position ``length + j`` — the device
        cannot allocate, so the block table must cover the whole block
        BEFORE dispatch.  Appends land at positions >= the prompt length,
        which always sit in the lane's private pages (the prefix cache
        only ever shares FULL prompt pages strictly below the write
        region), so pre-allocation can never hand the block a shared
        page to write.  Under pool pressure the block shrinks to what
        every participating lane can cover (snapped down onto
        BLOCK_K_MENU, surplus pages returned); a lane that cannot cover
        even one append skips this block entirely (same as the old
        per-tick starvation skip).  Returns ``(k_eff, [(lane, req,
        new_pages), ...])``.
        """
        parts = []
        cap = k
        for lane, req in decode_lanes:
            appends_want = max(1, min(k, req.steps - len(req.tokens_out)))
            need = (req.length + appends_want - 1) // self.page_size + 1
            new: List[int] = []
            while len(req.pages) < need:
                page = self._alloc_page()
                if page is None:
                    break
                req.pages.append(page)
                new.append(page)
            covered = len(req.pages) * self.page_size - req.length
            appends = min(appends_want, covered)
            if appends <= 0:
                for _ in new:  # starved: return the partial take
                    self.pool.release_pages([req.pages.pop()])
                continue
            if appends < appends_want:
                cap = min(cap, appends)
            parts.append((lane, req, new))
        if not parts:
            return k, []
        k_eff = max(m for m in self.BLOCK_K_MENU if m <= max(1, cap))
        if k_eff < k:
            # shrunk block: give back pages past the new write horizon
            for _lane, req, new in parts:
                appends_eff = max(1, min(k_eff,
                                         req.steps - len(req.tokens_out)))
                need = (req.length + appends_eff - 1) // self.page_size + 1
                while len(req.pages) > need and new:
                    self.pool.release_pages([req.pages.pop()])
                    new.pop()
        return k_eff, parts

    def _spec_eligible(self, req: _PagedRequest) -> bool:
        """May this lane ride a speculative dispatch?  Host-sampled
        (``top_k``/``top_p``/host-PRNG temperature) lanes never enter the
        speculative path — their picks need the logits row on host every
        token; degraded lanes (chaos verify trip, acceptance EWMA under
        the floor) stay plain for the rest of the request."""
        sp = req.sampling
        if sp.temperature > 0.0 and not sp.device:
            return False
        return req.spec_enabled

    def _degrade_spec(self, req: _PagedRequest,
                      probe: bool = False) -> None:
        """Drop the lane to plain decode blocks; its draft-table pages go
        straight back to the pool.  ``probe=True`` (the acceptance-EWMA
        path) schedules a periodic re-try: after ``SPEC_PROBE_INTERVAL``
        plain dispatches the lane runs ONE speculative probe block and
        recovers if acceptance came back — a transient degrade (an
        out-of-distribution stretch, a cold stretch after resume) stops
        being forever.  ``probe=False`` (chaos verify trips) stays plain
        for the rest of the request, as before."""
        if req.spec_enabled:
            req.spec_enabled = False
            self.spec_fallbacks += 1
        req.spec_probe_in = self.SPEC_PROBE_INTERVAL if probe else None
        req.spec_probing = False
        if req.draft_pages:
            self.pool.release_pages(req.draft_pages)
            req.draft_pages = []
        req.draft_len = 0

    def _probe_countdown_locked(self, req: _PagedRequest) -> None:
        """One plain dispatch elapsed for a transiently degraded lane.
        When the countdown hits zero the lane re-enters speculation as a
        PROBE: its EWMA is reset to the floor so the probe block's own
        acceptance decides — >= floor recovers the lane, < floor
        re-degrades and re-schedules the next probe."""
        if (self._spec is None or req.spec_enabled
                or req.spec_probe_in is None):
            return
        req.spec_probe_in -= 1
        if req.spec_probe_in > 0:
            return
        req.spec_probe_in = None
        req.spec_enabled = True
        req.spec_probing = True
        req.spec_ewma = self.spec_accept_floor
        self.spec_probes += 1

    def _reserve_spec_pages(self, decode_lanes, k: int):
        """Target + draft page reservation for one speculative block.

        A spec block writes ``k + 1`` positions (``lengths .. lengths+k``)
        on BOTH tables and emits up to ``k + 1`` accepted tokens.  Target
        pages are reserved FIRST (the plain fallback needs them
        regardless); under pool pressure the DRAFT table's shortfall
        shrinks the block k — it never steals or releases target pages.
        Pages past the (possibly shrunk) write horizon go straight back
        to the pool.  Returns ``(kd, parts)`` with ``parts`` entries
        ``(lane, req, new_target_pages, new_draft_pages)``; ``kd == 0``
        means the pool cannot support speculation this dispatch — the
        caller falls back to the plain path (surviving target
        reservations stay on the lanes for it, draft takes are
        returned)."""
        parts = []
        cap = k + 1                   # min covered appends across lanes
        for lane, req in decode_lanes:
            rem = req.steps - len(req.tokens_out)
            want = max(1, min(k + 1, rem))
            need = (req.length + want - 1) // self.page_size + 1
            new_t: List[int] = []
            while len(req.pages) < need:
                page = self._alloc_page()
                if page is None:
                    break
                req.pages.append(page)
                new_t.append(page)
            cov_t = len(req.pages) * self.page_size - req.length
            if cov_t <= 0:
                for _ in new_t:   # starved: return the partial take
                    self.pool.release_pages([req.pages.pop()])
                continue
            new_d: List[int] = []
            while len(req.draft_pages) < need:
                page = self._alloc_page()
                if page is None:
                    break
                req.draft_pages.append(page)
                new_d.append(page)
            cov_d = len(req.draft_pages) * self.page_size - req.length
            # only a COVERAGE shortfall shrinks the block: a lane whose
            # step budget is smaller than the block is handled by the
            # device-side steps-remaining mask (writes past the budget
            # route to scratch), exactly like plain blocks
            if cov_t < want:
                cap = min(cap, cov_t)
            if cov_d < want:
                cap = min(cap, cov_d)
            parts.append((lane, req, new_t, new_d))
        if not parts or cap < 2:
            # cannot cover even one proposal + its verify write: hand the
            # draft takes back; target reservations stay for plain blocks
            for _lane, req, _new_t, new_d in parts:
                for _ in new_d:
                    self.pool.release_pages([req.draft_pages.pop()])
            return 0, []
        kd = max(m for m in self.BLOCK_K_MENU if m <= cap - 1)
        for _lane, req, new_t, new_d in parts:
            rem = req.steps - len(req.tokens_out)
            want = max(1, min(kd + 1, rem))
            need = (req.length + want - 1) // self.page_size + 1
            while len(req.pages) > need and new_t:
                self.pool.release_pages([req.pages.pop()])
                new_t.pop()
            while len(req.draft_pages) > need and new_d:
                self.pool.release_pages([req.draft_pages.pop()])
                new_d.pop()
        return kd, parts

    def _plan_decode(self, snapshot):
        """Pick this dispatch's lanes, mode (speculative vs plain), block
        size, and page reservations.  The dispatch is speculative iff a
        draft model is armed and EVERY participating lane is eligible
        (one fused program serves the whole batch); otherwise — or when
        pool pressure cannot cover the draft tables — it is a plain
        block, which is the adaptive fallback the menu pick feeds."""
        decode_lanes = [(lane, req) for lane, req in enumerate(snapshot)
                        if req is not None and not req.cancelled
                        and not req.pending_prompt and req.tokens_out]
        if not decode_lanes:
            return None
        k = self._pick_block_k(decode_lanes)
        if (self._spec is not None
                and all(self._spec_eligible(r) for _, r in decode_lanes)):
            kd, parts = self._reserve_spec_pages(decode_lanes, k)
            if kd >= 1 and parts:
                return {"k": kd, "parts": parts, "mode": "spec"}
        k, parts = self._reserve_block_pages(decode_lanes, k)
        if not parts and any(req.draft_pages for _, req in decode_lanes):
            # every lane page-starved while draft tables hoard pages: the
            # draft KV is always regenerable, so treat pool pressure as a
            # TRANSIENT degrade — release the draft tables (arming the
            # probe countdown) and retry plain; without this the pool can
            # deadlock with target+draft tables holding every page
            for _lane, req in decode_lanes:
                if req.draft_pages:
                    self._degrade_spec(req, probe=True)
            k, parts = self._reserve_block_pages(
                decode_lanes, self._pick_block_k(decode_lanes))
        if not parts:
            return None  # every lane page-starved: caller backs off
        return {"k": k, "parts": parts, "mode": "plain"}

    def _tick(self, snapshot, jnp) -> bool:
        """One scheduler decode pass: consume the dispatched-ahead block
        if one is in flight, else plan + dispatch + consume.  Returns True
        when any lane made progress, False when every decode lane is
        starved (pool pressure) or idle."""
        if self._pending_block is not None:
            stash, self._pending_block = self._pending_block, None
            return self._consume_block(stash, jnp)
        plan = self._plan_decode(snapshot)
        if plan is None:
            return False
        if plan["mode"] == "spec":
            stash = self._dispatch_spec_block(plan["parts"], plan["k"], jnp)
            if stash is not None:
                return self._consume_spec_block(stash, jnp)
            # verify trip (chaos) pre-dispatch: the lanes just degraded to
            # plain — re-plan this tick as a plain block (their target
            # reservations are already in place)
            lanes = [(lane, req) for lane, req, _nt, _nd in plan["parts"]]
            k, parts = self._reserve_block_pages(
                lanes, self._pick_block_k(lanes))
            if not parts:
                return False
            plan = {"k": k, "parts": parts, "mode": "plain"}
        if plan["k"] == 1:
            return self._tick_single(plan["parts"], jnp)
        stash = self._dispatch_block(plan["parts"], plan["k"], jnp)
        return self._consume_block(stash, jnp)

    def _dispatch_block(self, parts, k: int, jnp, carry=None,
                        host=None):
        """Issue one K-step fused decode dispatch (async — no host sync).

        ``carry``/``host`` chain a follow-up block from a previous one's
        device-resident final state (dispatch-ahead overlap) — the block
        table is rebuilt host-side either way (new pages may have been
        reserved), but lengths/tokens/live/steps-remaining stay on device
        so chaining costs no round trip.
        """
        b = self.lanes
        tables = np.zeros((b, self.max_pages), np.int32)
        lane_reqs = {}
        for lane, req, _new in parts:
            lane_reqs[lane] = req
            tables[lane, :len(req.pages)] = req.pages
        if host is None:
            lengths = np.zeros((b,), np.int32)
            tokens = np.zeros((b,), np.int32)
            active = np.zeros((b,), bool)
            temps = np.zeros((b,), np.float32)
            seeds = np.zeros((b, 2), np.uint32)   # (lo, hi) words
            rem = np.zeros((b,), np.int32)
            n_stop = max((len(r.stop_tokens) for _, r, _ in parts),
                         default=0)
            width = (1 << (n_stop - 1).bit_length()) if n_stop > 1 else 1
            stops = np.full((b, width), -1, np.int32)  # ids >= 0: pad safe
            for lane, req, _new in parts:
                lengths[lane] = req.length
                tokens[lane] = req.tokens_out[-1]
                active[lane] = True
                rem[lane] = req.steps - len(req.tokens_out)
                sp = req.sampling
                if sp.device and sp.temperature > 0.0:
                    temps[lane] = sp.temperature
                    seeds[lane] = (sp.seed & 0xFFFFFFFF,
                                   (sp.seed >> 32) & 0xFFFFFFFF)
                if req.stop_tokens:
                    st = sorted(req.stop_tokens)
                    stops[lane, :len(st)] = st
        else:
            temps, seeds, stops = host
            lengths, tokens, active, rem = carry
        # chaos: decode fault site — tripped once per DECODE TICK (k times
        # per block), so a deterministic schedule written against
        # per-token serving (error@N, per-tick delays) keeps its meaning
        # under fused blocks; an error fails the in-flight requests and
        # resets the pool (the scheduler's recovery path)
        for _ in range(k):
            chaos.trip("engine.step")
        t0 = _time.perf_counter()
        (toks, lps, ems, len_f, tok_f, live_f, rem_f,
         self.pool.kv) = self._block_fn(k)(
            self.params, self.pool.kv, jnp.asarray(tables),
            jnp.asarray(lengths), jnp.asarray(tokens),
            jnp.asarray(active), jnp.asarray(temps), jnp.asarray(seeds),
            jnp.asarray(rem), jnp.asarray(stops))
        self.decode_dispatches += 1
        self._note_dispatch("decode")
        return {"k": k, "lane_reqs": lane_reqs, "dev": (toks, lps, ems),
                "carry": (len_f, tok_f, live_f, rem_f),
                "host": (temps, seeds, stops), "t0": t0}

    def _consume_block(self, stash, jnp) -> bool:
        """Fetch a dispatched block (ONE host sync for up to K tokens per
        lane) and unpack it through the per-token emit/trace/metrics
        path; may dispatch the NEXT block before running the emit
        callbacks (overlapping device compute with host-side emit)."""
        k = stash["k"]
        toks = np.asarray(stash["dev"][0], np.int32)
        lps = np.asarray(stash["dev"][1], np.float32)
        ems = np.asarray(stash["dev"][2], bool)
        self.decode_host_syncs += 1
        now = _time.perf_counter()  # post-fetch: device work is done
        self._step_ewma_s = (
            0.8 * self._step_ewma_s + 0.2 * ((now - stash["t0"]) / k)
            if self._step_ewma_s else (now - stash["t0"]) / k)
        emits: List = []
        completed: List = []
        clean = True        # every dispatched lane is still this request's
        emitted_total = 0
        with self._cv:
            for lane, req in stash["lane_reqs"].items():
                if self._active[lane] is not req or req.cancelled:
                    # released (cancel/deadline sweep) or preempted since
                    # dispatch: its block tokens are DISCARDED — a resume
                    # regenerates them exactly, a cancel never emits them
                    clean = False
                    continue
                self._probe_countdown_locked(req)
                n = int(ems[lane].sum())   # prefix mask: first n are valid
                if n == 0:
                    continue
                emitted_total += n
                # the block is one device round trip: spread its wall time
                # evenly over the lane's tokens so ITL keeps a true mean
                # (the burst shape is documented in docs/PERFORMANCE.md)
                dt = (now - req.t_last) / n if req.t_last is not None \
                    else None
                for j in range(n):
                    tok = int(toks[lane, j])
                    req.length += 1
                    req.tokens_out.append(tok)
                    self.tokens_generated += 1
                    if self.metrics is not None and dt is not None:
                        self.metrics.observe_itl(dt)
                    lp = float(lps[lane, j]) if req.want_logprobs else None
                    if req.want_logprobs:
                        req.logprobs_out.append(lp)
                    emits.append((req, tok, len(req.tokens_out) - 1, lp))
                req.t_last = now
                self._fl_block(req, k, n, dt)
                self._flush_decode_chunk(req, lane, now, block=k)
                if req.finished():
                    self._release_lane_locked(lane, req)
                    completed.append(req)
            self._admit_locked()
        if self.trace is not None and emitted_total:
            self.trace.add_counter("decode_block", now,
                                   tokens=emitted_total, k=k)
        # dispatch-ahead: with the lane set stable (nothing finished, no
        # cancel/preempt observed) and the SAME adaptive K still the right
        # choice, enqueue block N+1 from the device-resident carry BEFORE
        # running block N's callbacks — the next block computes while the
        # host emits.  Correctness never depends on this: a request
        # released between dispatch and consume has its block discarded
        # above, and its stale device writes only touch positions a new
        # page owner rewrites before reading.
        if (clean and not completed and k > 1
                and self._pending_block is None and not self._shutdown
                and not self._hbm_reclaim_bytes):
            lanes_now = list(stash["lane_reqs"].items())
            # a lane that just re-armed speculation (a probe countdown
            # expiring above) must flow back through _plan_decode — a
            # plain chain-ahead here would starve the probe forever
            spec_next = (self._spec is not None
                         and all(self._spec_eligible(r)
                                 for _, r in lanes_now))
            if not spec_next and self._pick_block_k(lanes_now) == k:
                k2, parts2 = self._reserve_block_pages(lanes_now, k)
                if k2 == k and len(parts2) == len(lanes_now):
                    self._pending_block = self._dispatch_block(
                        parts2, k, jnp, carry=stash["carry"],
                        host=stash["host"])
                # else: pages stay reserved on the lanes for the next
                # regular plan (bounded hoard: <= one block per lane)
        # user callbacks and future resolution OUTSIDE the scheduler lock:
        # a slow consumer must not head-of-line-block other lanes
        for req, tok, i, lp in emits:
            self._emit(req, tok, i, lp)
        for req in completed:
            if not req.future.done():
                self._flight_complete(req)
                req.future.set_result(self._result_of(req))
                self.completed_requests += 1
                self._note_complete(req)
        return True

    # -- speculative decode dispatch -----------------------------------------
    SPEC_EWMA_DECAY = 0.5   # per-dispatch acceptance EWMA smoothing

    #: plain dispatches a transiently degraded lane (acceptance EWMA under
    #: the floor) waits before one speculative PROBE block re-tries it;
    #: chaos-verify degrades never probe (plain for the rest of the request)
    SPEC_PROBE_INTERVAL = 4

    def _spec_block_fn(self, k: int):
        """Jitted speculative block (compiled once per draft length)."""
        fn = self._spec_block_cache.get(k)
        if fn is None:
            rep, kvsh = self._rep, self.pool.kv_sharding
            fn = self._jit(partial(paged_speculative_block, k=k,
                                   **self._spec_kw),
                           (2,),
                           (self._param_sh, self._draft_param_sh, kvsh)
                           + (rep,) * 9,
                           (rep,) * 9 + (kvsh,))
            self._spec_block_cache[k] = fn
        return fn

    def _warm_draft(self, req: _PagedRequest, jnp) -> None:
        """Bring the lane's draft KV up to the target context (positions
        ``[draft_len, length)``): one fused draft forward over the
        missing tail, scattered through the SECOND page table.  Costs a
        dispatch but never a host sync (the logits are not fetched).
        Runs at first speculative entry, after a preemption resume (the
        draft table is released at preemption and regenerated exactly
        here), and after plain-block interludes."""
        t = req.length
        if req.draft_len >= t:
            return
        ctx = np.concatenate([req.prompt,
                              np.asarray(req.tokens_out[:-1], np.int32)])
        start = req.draft_len
        m = t - start
        m_pad = 1 << (m - 1).bit_length()
        tokens = np.zeros((1, m_pad), np.int32)
        tokens[0, :m] = ctx[start:t]
        tables = np.zeros((self.max_pages,), np.int32)
        tables[:len(req.draft_pages)] = req.draft_pages
        _last, self.pool.kv = self._draft_extend(
            self._spec["params"], self.pool.kv, jnp.asarray(tables),
            jnp.asarray(tokens), jnp.int32(start), jnp.int32(t))
        req.draft_len = t
        self.spec_draft_prefills += 1

    def _dispatch_spec_block(self, parts, k: int, jnp):
        """Issue one fused speculative dispatch (draft-propose + verify +
        on-device accept).  Returns None when the verify trip point
        fires (chaos): the participating lanes degrade to plain blocks
        for the rest of their requests and NOTHING was dispatched — no
        token is ever emitted twice, corrupted, or lost."""
        # chaos: the speculative verify fault site — tripped once per
        # speculative dispatch, BEFORE anything is issued, so error/drop
        # degrade cleanly (the lanes' plain fallback re-decodes the very
        # same positions).  Exercised like kvcache.swap: degradation, not
        # request failure.
        try:
            tripped = chaos.trip("engine.verify")
        except chaos.ChaosError:
            tripped = "error"
        if tripped is not None:
            for _lane, req, _nt, _nd in parts:
                self._degrade_spec(req)
            return None
        for _lane, req, _nt, _nd in parts:
            self._warm_draft(req, jnp)
        b = self.lanes
        tables = np.zeros((b, self.max_pages), np.int32)
        dtables = np.zeros((b, self.max_pages), np.int32)
        lengths = np.zeros((b,), np.int32)
        tokens = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b, 2), np.uint32)   # (lo, hi) words
        rem = np.zeros((b,), np.int32)
        n_stop = max((len(r.stop_tokens) for _, r, _nt, _nd in parts),
                     default=0)
        width = (1 << (n_stop - 1).bit_length()) if n_stop > 1 else 1
        stops = np.full((b, width), -1, np.int32)  # ids >= 0: pad safe
        lane_reqs = {}
        for lane, req, _nt, _nd in parts:
            lane_reqs[lane] = req
            tables[lane, :len(req.pages)] = req.pages
            dtables[lane, :len(req.draft_pages)] = req.draft_pages
            lengths[lane] = req.length
            tokens[lane] = req.tokens_out[-1]
            active[lane] = True
            rem[lane] = req.steps - len(req.tokens_out)
            sp = req.sampling
            if sp.device and sp.temperature > 0.0:
                temps[lane] = sp.temperature
                seeds[lane] = (sp.seed & 0xFFFFFFFF,
                               (sp.seed >> 32) & 0xFFFFFFFF)
            if req.stop_tokens:
                st = sorted(req.stop_tokens)
                stops[lane, :len(st)] = st
        t0 = _time.perf_counter()
        (toks, lps, ems, _len_f, _tok_f, _live_f, _rem_f, drafted,
         accepted, self.pool.kv) = self._spec_block_fn(k)(
            self.params, self._spec["params"], self.pool.kv,
            jnp.asarray(tables), jnp.asarray(dtables),
            jnp.asarray(lengths), jnp.asarray(tokens), jnp.asarray(active),
            jnp.asarray(temps), jnp.asarray(seeds), jnp.asarray(rem),
            jnp.asarray(stops))
        self.decode_dispatches += 1
        self.spec_dispatches += 1
        self._note_dispatch("verify")
        return {"k": k, "lane_reqs": lane_reqs,
                "dev": (toks, lps, ems, drafted, accepted), "t0": t0}

    def _consume_spec_block(self, stash, jnp) -> bool:
        """Fetch a speculative dispatch (ONE host sync for up to K+1
        accepted tokens per lane), update each lane's acceptance EWMA,
        and unpack through the per-token emit/trace/metrics path.
        Drafted-but-rejected proposals are counted (``spec_tokens_*``)
        but never emitted and never enter ``tokens_generated`` — so
        tokens-per-dispatch telemetry reflects accepted tokens only."""
        k = stash["k"]
        toks = np.asarray(stash["dev"][0], np.int32)
        lps = np.asarray(stash["dev"][1], np.float32)
        ems = np.asarray(stash["dev"][2], bool)
        drafted = np.asarray(stash["dev"][3], np.int32)
        accepted = np.asarray(stash["dev"][4], np.int32)
        self.decode_host_syncs += 1
        now = _time.perf_counter()
        self._step_ewma_s = (
            0.8 * self._step_ewma_s + 0.2 * ((now - stash["t0"]) / (k + 1))
            if self._step_ewma_s else (now - stash["t0"]) / (k + 1))
        emits: List = []
        completed: List = []
        emitted_total = 0
        accepted_total = 0
        with self._cv:
            for lane, req in stash["lane_reqs"].items():
                if self._active[lane] is not req or req.cancelled:
                    continue  # released since dispatch: block discarded
                d, a = int(drafted[lane]), int(accepted[lane])
                self.spec_tokens_drafted += d
                self.spec_tokens_accepted += a
                req.spec_drafted += d
                req.spec_accepted += a
                accepted_total += a
                rate = a / d if d else 0.0
                req.spec_ewma = (self.SPEC_EWMA_DECAY * req.spec_ewma
                                 + (1.0 - self.SPEC_EWMA_DECAY) * rate)
                if req.spec_probing:
                    # this dispatch WAS the probe: its acceptance decides
                    req.spec_probing = False
                    if req.spec_ewma >= self.spec_accept_floor:
                        self.spec_probe_recoveries += 1
                if req.spec_ewma < self.spec_accept_floor:
                    self._degrade_spec(req, probe=True)
                n = int(ems[lane].sum())   # prefix mask: first n are valid
                if n == 0:
                    continue
                emitted_total += n
                dt = (now - req.t_last) / n if req.t_last is not None \
                    else None
                for j in range(n):
                    tok = int(toks[lane, j])
                    req.length += 1
                    req.tokens_out.append(tok)
                    self.tokens_generated += 1
                    if self.metrics is not None and dt is not None:
                        self.metrics.observe_itl(dt)
                    lp = float(lps[lane, j]) if req.want_logprobs else None
                    if req.want_logprobs:
                        req.logprobs_out.append(lp)
                    emits.append((req, tok, len(req.tokens_out) - 1, lp))
                req.t_last = now
                if req.draft_pages:
                    # the block's own draft writes cover every accepted
                    # position (k+1 scan iterations: no holes)
                    req.draft_len = req.length
                self._fl_block(req, k, n, dt)
                self._flush_decode_chunk(req, lane, now, block=k,
                                         accepted=a)
                if req.finished():
                    self._release_lane_locked(lane, req)
                    completed.append(req)
            self._admit_locked()
        if self.trace is not None and emitted_total:
            self.trace.add_counter("decode_block", now,
                                   tokens=emitted_total, k=k,
                                   accepted=accepted_total)
        # user callbacks and future resolution OUTSIDE the scheduler lock
        for req, tok, i, lp in emits:
            self._emit(req, tok, i, lp)
        for req in completed:
            if not req.future.done():
                self._flight_complete(req)
                req.future.set_result(self._result_of(req))
                self.completed_requests += 1
                self._note_complete(req)
        return True

    def _tick_single(self, parts, jnp) -> bool:
        """K=1 decode tick (host-sampled lanes present, or decode_block=1):
        one dispatch + one fetch per token, the pre-block behavior."""
        b = self.lanes
        tables = np.zeros((b, self.max_pages), np.int32)
        lengths = np.zeros((b,), np.int32)
        tokens = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        # device-sampled lanes carry their temperature into the step (the
        # tick then fetches only (B,)-sized arrays for them); host-sampled
        # (top_k/top_p) lanes keep temp 0 on device and pick from fetched
        # logits rows
        temps = np.zeros((b,), np.float32)
        seeds = np.zeros((b, 2), np.uint32)   # (lo, hi) words
        host_lanes = []
        want_logp = False
        lane_reqs = {}
        for lane, req, _new in parts:
            lane_reqs[lane] = req
            tokens[lane] = req.tokens_out[-1]
            tables[lane, :len(req.pages)] = req.pages
            lengths[lane] = req.length
            active[lane] = True
            want_logp |= req.want_logprobs
            sp = req.sampling
            if sp.temperature > 0.0:
                if sp.device:
                    temps[lane] = sp.temperature
                    seeds[lane] = (sp.seed & 0xFFFFFFFF,
                                   (sp.seed >> 32) & 0xFFFFFFFF)
                else:
                    host_lanes.append(lane)
        # chaos: decode-tick fault site — an error fails the in-flight
        # requests and resets the pool (the scheduler's recovery path); a
        # delay makes every lane's step slow (deadline-storm scenarios)
        chaos.trip("engine.step")
        t0 = _time.perf_counter()
        logprobs_arr = None
        if temps.any() or want_logp:
            tok_dev, logp_dev, logits, self.pool.kv = self._step_sampled(
                self.params, self.pool.kv,
                jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(tokens), jnp.asarray(active),
                jnp.asarray(temps), jnp.asarray(seeds))
            # greedy + device-sampled lanes: ONLY (B,)-sized arrays cross
            # the link (token ids + chosen-token logprobs)
            next_tokens = np.asarray(tok_dev, np.int32).copy()
            logprobs_arr = np.asarray(logp_dev, np.float32).copy()
        else:
            # neither device sampling nor logprobs this tick: the plain
            # step (no temps/seeds traced) — greedy stays one device
            # argmax
            logits, self.pool.kv = self._step(
                self.params, self.pool.kv,
                jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(tokens), jnp.asarray(active))
            next_tokens = np.asarray(logits.argmax(-1), np.int32).copy()
        self.decode_dispatches += 1
        self._note_dispatch("decode")
        self.decode_host_syncs += 1
        if host_lanes:
            # fetch ONLY the host-sampled rows: gather them device-side,
            # then one (n_host, vocab) transfer — not the full
            # (lanes, vocab) matrix when a single lane host-samples.
            # Only active host-sampled lanes consume PRNG state: a
            # page-starved or pending-prefill lane must not perturb a
            # seeded request's token sequence (per-request reproducibility)
            rows = np.asarray(
                logits[jnp.asarray(np.asarray(host_lanes, np.int32))])
            self.decode_host_syncs += 1
            for i, lane in enumerate(host_lanes):
                next_tokens[lane] = lane_reqs[lane].sampling.pick(rows[i])
                if logprobs_arr is not None:
                    # f32 log-sum-exp: the same precision class as the
                    # device log_softmax used for prefill and for
                    # device-sampled lanes — one request, one precision
                    row = rows[i].astype(np.float32)
                    row = row - row.max()
                    logprobs_arr[lane] = float(
                        row[next_tokens[lane]]
                        - np.log(np.exp(row).sum()))

        emits: List = []
        completed: List = []
        now = _time.perf_counter()  # post-fetch: the tick's device work is
        #                             done, so per-lane deltas are real
        self._step_ewma_s = (0.8 * self._step_ewma_s + 0.2 * (now - t0)
                             if self._step_ewma_s else now - t0)
        with self._cv:
            for lane, req in lane_reqs.items():
                if req.cancelled:
                    continue  # the _run sweep releases it next round
                self._probe_countdown_locked(req)
                req.length += 1
                req.tokens_out.append(int(next_tokens[lane]))
                self.tokens_generated += 1
                if self.metrics is not None and req.t_last is not None:
                    self.metrics.observe_itl(now - req.t_last)
                self._fl_block(req, 1, 1,
                               (now - req.t_last)
                               if req.t_last is not None else None)
                req.t_last = now
                lp = (float(logprobs_arr[lane])
                      if logprobs_arr is not None else None)
                if req.want_logprobs:
                    req.logprobs_out.append(lp)
                emits.append((req, req.tokens_out[-1],
                              len(req.tokens_out) - 1, lp))
                done = req.finished()
                if (done or len(req.tokens_out) - req.chunk_start
                        >= self.TRACE_DECODE_CHUNK):
                    self._flush_decode_chunk(req, lane, now)
                if done:
                    self._release_lane_locked(lane, req)
                    completed.append(req)
            self._admit_locked()
        # user callbacks and future resolution OUTSIDE the scheduler lock:
        # a slow consumer must not head-of-line-block other lanes
        for req, tok, i, lp in emits:
            self._emit(req, tok, i, lp)
        for req in completed:
            if not req.future.done():
                self._flight_complete(req)
                req.future.set_result(self._result_of(req))
                self.completed_requests += 1
                self._note_complete(req)
        return True

    @staticmethod
    def _result_of(req: _PagedRequest):
        toks = list(req.tokens_out[:req.steps])
        if req.want_logprobs:
            return toks, list(req.logprobs_out[:len(toks)])
        return toks

    def _release_lane_locked(self, lane: int, req: _PagedRequest) -> None:
        if (req.export_digest is not None and self.kv_offload is not None
                and not req.cancelled and req.length > 0
                and req.finished()):
            # disagg export: demote the finished KV to the host tier
            # BEFORE the pages are released (dispatch order makes the
            # gather safe — same window as preemption swap-out).  The
            # handle rides the future; the shipper's export wait is the
            # write-behind fence.
            needed = (req.length + self.page_size - 1) // self.page_size
            req.future._tpulab_kv_export = self.kv_offload.swap_out(
                req.pages[:needed], req.length, self.pool.kv,
                key=("ship", req.export_digest))
        self.pool.release_pages(req.pages)
        if req.draft_pages:
            self.pool.release_pages(req.draft_pages)
            req.draft_pages = []
        self._discard_handle(req)  # a cancelled resume never restores
        self._active[lane] = None
        self._requests.pop(req.future, None)


def _timed_decode_tok_s(step, params_dev, kv0, tables, lengths, tokens,
                        active, lanes: int, iters: int) -> float:
    """Scan-chained, fetch-fenced decode timing (the load-bearing bench
    discipline: all iters ride ONE dispatch via lax.scan — through a relay
    tunnel per-dispatch RTT is tens of ms and would measure the link — and
    the fence is a host fetch of the tiny logits trace, because
    block_until_ready does NOT guarantee execution completed on
    remote-relay backends).  Returns best-of-2 tokens/s."""
    import time

    import jax

    @partial(jax.jit, donate_argnums=(1,))
    def run_n(p, kv, tables, lengths, tokens, active):
        def body(kv, _):
            logits, kv = step(p, kv, tables, lengths, tokens, active)
            return kv, logits[0, 0]
        kv, ls = jax.lax.scan(body, kv, None, length=iters)
        return ls, kv

    ls, kv = run_n(params_dev, kv0, tables, lengths, tokens, active)
    np.asarray(ls)  # compile + warm (fetch = execution fence)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        ls, kv = run_n(params_dev, kv, tables, lengths, tokens, active)
        np.asarray(ls)
        best = min(best, time.perf_counter() - t0)
    return lanes * iters / best


def benchmark_decode_kernel_vs_gather(n_heads: int = 8, n_layers: int = 4,
                                      d_model: int = 1024,
                                      page_size: int = 32, lanes: int = 8,
                                      ctx: int = 2048, iters: int = 256,
                                      dtype=None,
                                      autotune: bool = True
                                      ) -> Dict[str, Any]:
    """tokens/s of the pallas ragged-paged-attention decode vs the XLA
    gather fallback at one long-context geometry (the bench perf row and
    the hardware test share this; VERDICT round-1 #3).

    ``autotune`` additionally times the kernel at neighboring block
    geometries (g_pages halved/doubled around the auto pick) and records
    the per-geometry numbers — one capture then attributes a win or loss
    to block size instead of requiring another hardware round
    (VERDICT r3 #3: "if it loses, profile where and iterate")."""
    import jax.numpy as jnp

    from tpulab.models.transformer import init_transformer_params
    from tpulab.ops.paged_attention import _block_geometry

    dtype = dtype or jnp.bfloat16
    mp = ctx // page_size
    params = init_transformer_params(vocab=256, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    tables = np.arange(1, lanes * mp + 1, dtype=np.int32).reshape(lanes, mp)
    lengths = np.full((lanes,), ctx - 2, np.int32)
    tokens = np.zeros((lanes,), np.int32)
    active = np.ones((lanes,), bool)
    row: Dict[str, Any] = {"b": lanes, "ctx": ctx}

    def timed(uk, geometry=None, n_iters=iters):
        pool = PagedKVPool(lanes * mp + 1, page_size, n_layers, n_heads,
                           d_model // n_heads, dtype)
        try:
            step = partial(
                paged_decode_step, n_heads=n_heads, n_layers=n_layers,
                compute_dtype=dtype, use_kernel=uk,
                kernel_geometry=geometry)
            return round(_timed_decode_tok_s(
                step, params, pool.kv, tables, lengths, tokens, active,
                lanes, n_iters), 1), None
        except Exception as e:
            return 0.0, f"{type(e).__name__}: {str(e)[:160]}"
        finally:
            pool.close()

    row["kernel_tok_s"], err = timed(True)
    if err:
        row["kernel_error"] = err
    row["gather_tok_s"], err = timed(False)
    if err:
        row["gather_error"] = err
    # the kernel's internal auto-pick is hkv*d (paged_attention.py); this
    # model is MHA so hkv == n_heads, but derive it the same way so the
    # recorded geometry stays honest if a GQA variant joins the sweep
    hkv = n_heads  # init_transformer_params above builds an MHA model
    g0, n0 = _block_geometry(page_size, mp, hkv * (d_model // n_heads),
                             jnp.dtype(dtype).itemsize)
    row["kernel_geom"] = f"g{g0}xn{n0}"
    if autotune and "kernel_error" not in row:
        tune = {row["kernel_geom"]: row["kernel_tok_s"]}
        for g in {max(1, g0 // 2), min(2 * g0, mp)} - {g0}:
            # keep g*nbuf (total staged pages, hence VMEM scratch) at the
            # auto pick's level: doubling g with n0 buffers would double
            # the scratch past the kernel's VMEM budget and fail compile
            nb = max(2, min(n0, (g0 * n0) // g))
            tok_s, err = timed(True, geometry=(g, nb),
                               n_iters=max(16, iters // 2))
            tune[f"g{g}xn{nb}"] = tok_s if not err else err
        row["kernel_autotune"] = tune
        numeric = {k: v for k, v in tune.items() if isinstance(v, float)}
        best = max(numeric, key=numeric.get)
        row["kernel_best_tok_s"] = numeric[best]
        row["kernel_best_geom"] = best
    return row


def benchmark_decode_kernel_sweep(
        combos=((8, 2048), (32, 2048), (8, 8192), (8, 16384)),
        n_heads: int = 8, n_layers: int = 4, d_model: int = 1024,
        page_size: int = 32, dtype=None) -> List[Dict[str, Any]]:
    """Kernel-vs-gather across (batch, context) — where the gather's
    O(B*ctx) HBM materialization explodes and the ragged walk should pull
    ahead (VERDICT round-2 #3).  Iteration counts scale inversely with
    per-step work to keep wall time bounded."""
    rows = []
    for lanes, ctx in combos:
        iters = max(16, int(256 * (8 * 2048) / (lanes * ctx)))
        rows.append(benchmark_decode_kernel_vs_gather(
            n_heads=n_heads, n_layers=n_layers, d_model=d_model,
            page_size=page_size, lanes=lanes, ctx=ctx, iters=iters,
            dtype=dtype,
            # bound first-capture compile time: geometry variants only at
            # the shorter contexts (the 16k point is one geometry)
            autotune=ctx <= 8192))
    return rows


def benchmark_decode_dispatch(ks=(1, 4, 8, 16), lanes: int = 4,
                              steps: int = 48, prompt_len: int = 8,
                              d_model: int = 64, n_heads: int = 4,
                              n_layers: int = 2, vocab: int = 256,
                              dtype=None) -> Dict[str, Any]:
    """Served tokens/s and host-sync accounting of the ContinuousBatcher
    across fused-decode block sizes K (the bench ``decode_dispatch`` row).

    The same submit->result workload runs at each K; per K the row
    records tok/s, decode dispatches, blocking host syncs, and
    syncs-per-token, plus greedy token parity against the K=1 run.  On
    CPU jit the dispatch/sync counts are the signal (there is no link
    RTT to amortize); on-device the tok/s uplift is — off-chip, the
    per-token cost IS the round trip, so tok/s should scale toward the
    kernel rate as K grows.
    """
    import time

    import jax.numpy as jnp

    from tpulab.models.transformer import init_transformer_params

    dtype = dtype or jnp.float32
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(lanes)]
    max_len = prompt_len + steps + 8
    row: Dict[str, Any] = {"lanes": lanes, "steps": steps, "k": {}}
    base_tokens = None
    for k in ks:
        cb = ContinuousBatcher(params, n_heads=n_heads, n_layers=n_layers,
                               lanes=lanes, max_len=max_len, page_size=8,
                               compute_dtype=dtype, decode_block=k)
        try:
            # warm the prefill/decode compiles out of the measurement
            for f in [cb.submit(p, steps) for p in prompts]:
                f.result(timeout=600)
            d0, s0 = cb.decode_dispatches, cb.decode_host_syncs
            tg0 = cb.tokens_generated
            t0 = time.perf_counter()
            futs = [cb.submit(p, steps) for p in prompts]
            outs = [list(f.result(timeout=600)) for f in futs]
            dt = time.perf_counter() - t0
            toks = cb.tokens_generated - tg0
            entry = {
                "tok_s": round(toks / max(dt, 1e-9), 1),
                "dispatches": cb.decode_dispatches - d0,
                "host_syncs": cb.decode_host_syncs - s0,
                "syncs_per_token": round(
                    (cb.decode_host_syncs - s0) / max(toks, 1), 4),
            }
            if base_tokens is None:
                base_tokens = outs
            else:
                entry["parity_vs_k1"] = outs == base_tokens
            row["k"][str(k)] = entry
        except Exception as e:  # one K's failure must not sink the row
            row["k"][str(k)] = {
                "error": f"{type(e).__name__}: {str(e)[:160]}"}
        finally:
            cb.shutdown()
    k1 = row["k"].get("1", {})
    best = max((e for e in row["k"].values() if "tok_s" in e),
               key=lambda e: e["tok_s"], default=None)
    if best is not None and k1.get("tok_s"):
        row["best_tok_s"] = best["tok_s"]
        row["uplift_vs_k1"] = round(best["tok_s"] / k1["tok_s"], 3)
    return row


def benchmark_speculative_decode(k: int = 8, lanes: int = 2,
                                 steps: int = 48, prompt_len: int = 8,
                                 d_model: int = 64, n_heads: int = 4,
                                 n_layers: int = 4, draft_layers: int = 1,
                                 vocab: int = 256,
                                 tail_scale: float = 0.05,
                                 dtype=None) -> Dict[str, Any]:
    """tok/s, tokens-per-dispatch, host syncs, and acceptance rate of
    speculative decode blocks vs plain K-blocks through the SAME
    ContinuousBatcher workload (the bench ``speculative_decode`` row).

    Supersedes the dense-path ``benchmark_speculative`` row for capture
    purposes: both modes here share one serving-shaped workload function,
    so there is no duplicated plain-baseline loop, and greedy parity is
    recorded in the row like ``decode_dispatch`` does.  The draft is the
    target's first ``draft_layers`` layers (early-exit) with the
    post-exit output projections scaled by ``tail_scale`` — the
    trained-model emulation :func:`benchmark_speculative` documents
    (raw random tail layers pin acceptance to 0 and measure nothing).

    On the CPU capture path the dispatch/sync/acceptance counts are the
    signal (no link RTT to amortize); on-device the tok/s uplift is —
    speculation multiplies the K-block amortization by the acceptance
    rate, so off-chip served tok/s scales with ``(1 + acceptance*k)``
    per round trip.
    """
    import time

    import jax.numpy as jnp

    from tpulab.models.transformer import (early_exit_draft,
                                           init_transformer_params)

    dtype = dtype or jnp.float32
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    for i in range(draft_layers, n_layers):  # see tail_scale docstring
        for w in ("wo", "w2"):
            params[f"layer{i}"][w] = params[f"layer{i}"][w] * tail_scale
    draft = early_exit_draft(params, draft_layers)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(lanes)]
    max_len = prompt_len + steps + 8
    row: Dict[str, Any] = {"lanes": lanes, "steps": steps, "k": k,
                           "draft_layers": draft_layers}
    outs: Dict[str, Any] = {}
    for mode in ("plain", "spec"):
        cb = ContinuousBatcher(
            params, n_heads=n_heads, n_layers=n_layers, lanes=lanes,
            max_len=max_len, page_size=8, compute_dtype=dtype,
            decode_block=k,
            n_pages=2 * lanes * ((max_len + 7) // 8) + 1,
            draft_params=draft if mode == "spec" else None,
            draft_n_layers=draft_layers)
        try:
            # warm the prefill/decode/draft compiles out of the measurement
            for f in [cb.submit(p, steps) for p in prompts]:
                f.result(timeout=600)
            # deterministically pre-compile EVERY block size the adaptive
            # scheduler may pick: which sizes a live warm run hits depends
            # on admission interleaving and per-lane acceptance
            # trajectories, and a compile landing in the measured window
            # would swamp the tok/s signal.  A zero throwaway pool
            # satisfies the donated argument without touching the live one.
            base = (jnp.zeros((lanes, cb.max_pages), jnp.int32),
                    jnp.zeros((lanes,), jnp.int32),
                    jnp.zeros((lanes,), jnp.int32),
                    jnp.zeros((lanes,), bool))
            extra = (jnp.zeros((lanes,), jnp.float32),
                     jnp.zeros((lanes, 2), jnp.uint32),
                     jnp.zeros((lanes,), jnp.int32),
                     jnp.full((lanes, 1), -1, jnp.int32))
            for m in cb.BLOCK_K_MENU:
                if m > k:
                    continue
                zkv = jnp.zeros(cb.pool.kv.shape, cb.pool.kv.dtype)
                if mode == "spec":
                    out = cb._spec_block_fn(m)(cb.params,
                                               cb._spec["params"], zkv,
                                               base[0], *base, *extra)
                elif m > 1:   # k=1 plain runs _tick_single's step
                    out = cb._block_fn(m)(cb.params, zkv, *base, *extra)
                else:
                    continue
                np.asarray(out[0])    # fetch = compile fence
            d0, s0 = cb.decode_dispatches, cb.decode_host_syncs
            tg0 = cb.tokens_generated
            dr0, ac0 = cb.spec_tokens_drafted, cb.spec_tokens_accepted
            t0 = time.perf_counter()
            futs = [cb.submit(p, steps) for p in prompts]
            outs[mode] = [list(f.result(timeout=600)) for f in futs]
            dt = time.perf_counter() - t0
            toks = cb.tokens_generated - tg0
            entry = {
                "tok_s": round(toks / max(dt, 1e-9), 1),
                "dispatches": cb.decode_dispatches - d0,
                "host_syncs": cb.decode_host_syncs - s0,
                # accepted (emitted) tokens only: drafted-but-rejected
                # proposals never enter tokens_generated
                "tokens_per_dispatch": round(
                    toks / max(1, cb.decode_dispatches - d0), 2),
                "syncs_per_token": round(
                    (cb.decode_host_syncs - s0) / max(toks, 1), 4),
            }
            if mode == "spec":
                drafted = cb.spec_tokens_drafted - dr0
                accepted = cb.spec_tokens_accepted - ac0
                entry["drafted"] = drafted
                entry["accepted"] = accepted
                entry["acceptance"] = round(accepted / max(1, drafted), 3)
                entry["fallbacks"] = cb.spec_fallbacks
            row[mode] = entry
        except Exception as e:  # one mode's failure must not sink the row
            row[mode] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
        finally:
            cb.shutdown()
    if "tok_s" in row.get("plain", {}) and "tok_s" in row.get("spec", {}):
        row["parity"] = outs["spec"] == outs["plain"]
        row["uplift"] = round(row["spec"]["tok_s"]
                              / max(row["plain"]["tok_s"], 1e-9), 3)
    return row


def benchmark_sharded_decode(model_shards: int = 2, lanes: int = 4,
                             steps: int = 32, prompt_len: int = 8,
                             d_model: int = 64, n_heads: int = 4,
                             n_layers: int = 2, vocab: int = 256,
                             decode_block: int = 8,
                             dtype=None) -> Dict[str, Any]:
    """Served tok/s and host-sync accounting of ONE ContinuousBatcher
    workload on a ``{"model": M}`` device mesh vs single-device (the
    bench ``sharded_decode`` row).

    Needs >= ``model_shards`` jax devices: the CPU capture path runs
    under ``--xla_force_host_platform_device_count``-style fake devices
    (bench.py spawns this in a subprocess with 8), where the signal is
    token parity plus the PRESERVED dispatch/host-sync counts — XLA's
    inserted collectives ride inside the fused block program, so the
    one-host-sync-per-block contract survives sharding.  On a real
    multi-chip slice the signal is tok/s with a model (and KV pool)
    bigger than one chip's HBM.  Greedy parity is recorded like the
    ``decode_dispatch``/``speculative_decode`` rows; one seeded
    device-sampled request rides along for ``sampled_parity``.
    """
    import time

    import jax
    import jax.numpy as jnp

    from tpulab.models.transformer import init_transformer_params
    from tpulab.parallel.mesh import make_mesh

    dtype = dtype or jnp.float32
    if len(jax.devices()) < model_shards:
        return {"error": f"needs {model_shards} devices, "
                         f"have {len(jax.devices())}"}
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(lanes)]
    max_len = prompt_len + steps + 8
    row: Dict[str, Any] = {"lanes": lanes, "steps": steps,
                           "mesh": {"model": model_shards},
                           "decode_block": decode_block}
    outs: Dict[str, Any] = {}
    sampled: Dict[str, Any] = {}
    for mode in ("single", "sharded"):
        mesh = (make_mesh({"model": model_shards},
                          jax.devices()[:model_shards])
                if mode == "sharded" else None)
        cb = ContinuousBatcher(params, n_heads=n_heads, n_layers=n_layers,
                               lanes=lanes, max_len=max_len, page_size=8,
                               compute_dtype=dtype,
                               decode_block=decode_block, mesh=mesh)
        try:
            # warm the prefill/decode compiles out of the measurement
            for f in [cb.submit(p, steps) for p in prompts]:
                f.result(timeout=600)
            d0, s0 = cb.decode_dispatches, cb.decode_host_syncs
            tg0 = cb.tokens_generated
            t0 = time.perf_counter()
            futs = [cb.submit(p, steps) for p in prompts]
            outs[mode] = [list(f.result(timeout=600)) for f in futs]
            dt = time.perf_counter() - t0
            toks = cb.tokens_generated - tg0
            row[mode] = {
                "tok_s": round(toks / max(dt, 1e-9), 1),
                "dispatches": cb.decode_dispatches - d0,
                "host_syncs": cb.decode_host_syncs - s0,
                "syncs_per_token": round(
                    (cb.decode_host_syncs - s0) / max(toks, 1), 4),
            }
            # a seeded device-sampled stream must survive sharding too
            sampled[mode] = list(cb.submit(
                prompts[0], steps,
                sampling=SamplingParams(temperature=0.8, seed=1234,
                                        device=True)).result(timeout=600))
        except Exception as e:  # one mode's failure must not sink the row
            row[mode] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
        finally:
            cb.shutdown()
    if "tok_s" in row.get("single", {}) and "tok_s" in row.get("sharded", {}):
        row["parity"] = outs["sharded"] == outs["single"]
        row["sampled_parity"] = sampled["sharded"] == sampled["single"]
        # the sharding contract is per-DISPATCH: collectives stay inside
        # the compiled block, so every dispatch costs exactly one
        # blocking fetch in both modes.  (Raw cross-mode dispatch counts
        # can differ by a timing-dependent dispatch-ahead block that
        # emits nothing, so they are reported, not compared.)
        row["one_sync_per_dispatch"] = all(
            row[m]["host_syncs"] == row[m]["dispatches"]
            for m in ("single", "sharded"))
        row["uplift"] = round(row["sharded"]["tok_s"]
                              / max(row["single"]["tok_s"], 1e-9), 3)
    return row


def benchmark_ragged_attention(lanes: int = 3, steps: int = 24,
                               prompt_len: int = 12, d_model: int = 64,
                               n_heads: int = 4, n_layers: int = 2,
                               vocab: int = 256,
                               kernel: bool = True,
                               dtype=None) -> Dict[str, Any]:
    """Dispatch/host-sync accounting + served tok/s of the ragged
    dispatch plan across batch-raggedness shapes (the bench
    ``ragged_attention`` row).

    Three workload shapes through the SAME submit->result harness:
    ``all_prefill`` (``lanes`` simultaneous steps=1 prompts — the shape
    where the unified plan folds N per-lane prefill programs into ONE
    fused dispatch), ``all_decode`` (the K-block regime, unchanged by
    the plan), and ``mixed`` (prompts arriving mid-decode — the round
    that previously cost separate prefill dispatches plus a decode
    block).  Modes: ``legacy`` (split dispatch, the use_kernel=False
    escape hatch), ``ragged`` (unified plan, XLA gather attention), and
    ``ragged_kernel`` (unified plan, pallas ragged kernel — interpret
    mode on the CPU capture path, so its tok/s there measures the
    interpreter, not the kernel; dispatch/sync counts and parity are
    the CPU signal).  Token parity vs legacy is recorded per shape.
    """
    import threading as _threading
    import time

    import jax.numpy as jnp

    from tpulab.models.transformer import init_transformer_params

    dtype = dtype or jnp.float32
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(lanes)]
    max_len = prompt_len + steps + 8
    modes = [("legacy", dict(use_kernel=False)),
             ("ragged", dict(use_kernel=False, ragged=True))]
    if kernel:
        modes.append(("ragged_kernel", dict(use_kernel=True)))
    row: Dict[str, Any] = {"lanes": lanes, "steps": steps,
                           "prompt_len": prompt_len}
    outs: Dict[str, Dict[str, Any]] = {}
    for mode, kw in modes:
        cb = ContinuousBatcher(params, n_heads=n_heads, n_layers=n_layers,
                               lanes=lanes, max_len=max_len, page_size=8,
                               compute_dtype=dtype, decode_block=8, **kw)
        entry: Dict[str, Any] = {}
        got: Dict[str, Any] = {}
        try:
            # warm every program shape out of the measurements
            for f in [cb.submit(p, steps) for p in prompts]:
                f.result(timeout=600)
            cb.submit(prompts[0], 1).result(timeout=600)

            def window(name, fn):
                d0 = (cb.decode_dispatches + cb.prefill_dispatches,
                      cb.decode_host_syncs, cb.tokens_generated)
                t0 = time.perf_counter()
                got[name] = fn()
                dt = time.perf_counter() - t0
                toks = cb.tokens_generated - d0[2]
                entry[name] = {
                    "tok_s": round(toks / max(dt, 1e-9), 1),
                    "dispatches": (cb.decode_dispatches
                                   + cb.prefill_dispatches - d0[0]),
                    "host_syncs": cb.decode_host_syncs - d0[1],
                    "syncs_per_token": round(
                        (cb.decode_host_syncs - d0[1]) / max(toks, 1), 4),
                }

            def all_prefill():
                futs = [cb.submit(p, 1) for p in prompts]
                return [list(f.result(timeout=600)) for f in futs]

            def all_decode():
                futs = [cb.submit(p, steps) for p in prompts]
                return [list(f.result(timeout=600)) for f in futs]

            def mixed():
                evt = _threading.Event()
                hook = (lambda t, i: evt.set() if i == 2 else None)
                f0 = cb.submit(prompts[0], steps, on_token=hook)
                evt.wait(60)
                rest = [cb.submit(p, steps // 2) for p in prompts[1:]]
                return ([list(f0.result(timeout=600))]
                        + [list(f.result(timeout=600)) for f in rest])

            window("all_prefill", all_prefill)
            window("all_decode", all_decode)
            window("mixed", mixed)
            entry["ragged_dispatches"] = cb.ragged_dispatches
            entry["dispatch_kinds"] = dict(cb.dispatch_kinds)
            outs[mode] = got
            row[mode] = entry
        except Exception as e:  # one mode's failure must not sink the row
            row[mode] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
        finally:
            cb.shutdown()
    base = outs.get("legacy")
    if base:
        for mode in ("ragged", "ragged_kernel"):
            if mode in outs:
                # all_prefill/all_decode are deterministic across modes;
                # the mixed window's token VALUES are too (its arrival
                # timing only changes dispatch grouping)
                row[mode]["parity"] = outs[mode] == base
        if "ragged" in row and "dispatches" in row["ragged"].get(
                "all_prefill", {}):
            row["prefill_fold"] = {
                "legacy_dispatches":
                    row["legacy"]["all_prefill"]["dispatches"],
                "ragged_dispatches":
                    row["ragged"]["all_prefill"]["dispatches"]}
    return row


def benchmark_llm_decode(n_heads: int = 16, n_kv_heads: int = 4,
                         n_layers: int = 8, d_model: int = 1024,
                         d_ff: int = 4096, vocab: int = 8192,
                         page_size: int = 16, lanes: int = 8,
                         ctx: int = 1024, iters: int = 64,
                         dtype=None) -> Dict[str, Any]:
    """Paged decode tokens/s with bf16 vs weight-only-int8 params (W8A16)
    at a Llama-ish GQA geometry — small-batch decode is weight-bandwidth
    bound, so int8 weights are the serving-latency lever this row
    measures.  Same scan-chained, fetch-fenced discipline as
    :func:`benchmark_decode_kernel_vs_gather`."""
    import jax
    import jax.numpy as jnp

    from tpulab.models.quantization import (quantize_transformer_params,
                                            transformer_param_bytes)
    from tpulab.models.transformer import init_transformer_params

    dtype = dtype or jnp.bfloat16

    def to_bf16(tree):
        # cast every float leaf; int8 payloads pass through untouched
        return jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            tree)

    mp = ctx // page_size
    # untied head so the LARGEST per-step weight read (lm_head) is part of
    # what quantization shrinks; the int8 variant's remaining float leaves
    # (embed, norms, scales) are bf16 like the baseline — the comparison
    # isolates exactly the weight-width axis
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=d_ff, n_kv_heads=n_kv_heads,
                                     tie_embeddings=False)
    variants = {
        "bf16": to_bf16(params),
        "int8": to_bf16(quantize_transformer_params(params)),
    }
    tables = np.arange(1, lanes * mp + 1, dtype=np.int32).reshape(lanes, mp)
    lengths = np.full((lanes,), ctx - 2, np.int32)
    tokens = np.zeros((lanes,), np.int32)
    active = np.ones((lanes,), bool)
    row: Dict[str, Any] = {"b": lanes, "ctx": ctx,
                           "layers": n_layers, "d_model": d_model}
    for label, p in variants.items():
        pool = PagedKVPool(lanes * mp + 1, page_size, n_layers, n_kv_heads,
                           d_model // n_heads, dtype)
        try:
            step = partial(paged_decode_step, n_heads=n_heads,
                           n_layers=n_layers, compute_dtype=dtype,
                           use_kernel=False, n_kv_heads=n_kv_heads)
            pdev = jax.device_put(p, pool.device)
            row[f"{label}_tok_s"] = round(_timed_decode_tok_s(
                step, pdev, pool.kv, tables, lengths, tokens, active,
                lanes, iters), 1)
            row[f"{label}_param_mb"] = round(
                transformer_param_bytes(p) / 2**20, 1)
        except Exception as e:
            row[f"{label}_tok_s"] = 0.0
            row[f"{label}_error"] = f"{type(e).__name__}: {str(e)[:160]}"
        finally:
            pool.close()
    return row
