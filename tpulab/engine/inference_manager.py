"""InferenceManager: the central resource manager
(reference inference_manager.cc:59-330).

Owns, exactly as the reference does:
- registered models -> CompiledModels (per-bucket executables, weights in HBM)
- a global ``Pool[Buffers]`` of staging bundles sized to the largest
  registered model (max-reduce over models, reference :110-117), with
  ``max_buffers = 2 * max_executions`` by default (reference :59-62) so one
  H2D, N computes, and one D2H overlap (SURVEY §2.8 axis 2)
- a global execution-token ``Pool`` bounding in-flight dispatches plus a
  per-model ``Pool[ExecutionContext]`` — ``get_execution_context`` does the
  two-level pop (global token, then model slot; reference :254-273) and both
  block when exhausted: natural backpressure
- named thread pools ("pre", "dispatch", "post"; reference "pre"/"cuda"/"post")
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Iterable, Optional

from tpulab.core.pool import Pool, PoolItem, make_serving_pool
from tpulab.core.thread_pool import ThreadPool
from tpulab.engine.buffers import Buffers
from tpulab.engine.execution_context import ExecutionContext
from tpulab.engine.model import Model
from tpulab.engine.runtime import CompiledModel, Runtime
from tpulab.tpu import platform as plat

log = logging.getLogger("tpulab.engine")


class InferenceManager:
    """Pools + models + thread pools (reference InferenceManager)."""

    def __init__(self, max_executions: int = 2, max_buffers: int = 0,
                 device=None, coalesce_h2d: bool = True):
        if max_executions < 1:
            raise ValueError("max_executions must be >= 1")
        self.max_executions = max_executions
        self.max_buffers = max_buffers or 2 * max_executions  # reference :59-62
        # default-on batched input puts: concurrent requests share one
        # jax.device_put per collector cycle (a lone put still ships
        # immediately — the collector drains as soon as it is signaled, so
        # depth-1 latency only pays one thread handoff)
        self.coalesce_h2d = coalesce_h2d
        self.device = device if device is not None else plat.local_device(0)
        self._runtime = Runtime(self.device)
        self._models: Dict[str, Model] = {}
        self._compiled: Dict[str, CompiledModel] = {}
        self._ctx_pools: Dict[str, Pool[ExecutionContext]] = {}
        self._buffers_pool: Optional[Pool[Buffers]] = None
        self._exec_tokens: Optional[Pool[int]] = None
        self._transfer_engine = None
        self._event_poller = None
        self._thread_pools: Dict[str, ThreadPool] = {}
        self._lock = threading.Lock()
        self._allocated = False

    # -- registration (reference RegisterModel :92-156) ---------------------
    def register_model(self, name: str, model: Model,
                       max_concurrency: Optional[int] = None) -> None:
        """Compile + register; per-model context slots = max_concurrency
        (default: manager max_executions, reference :151-155)."""
        if self._allocated:
            raise RuntimeError("register models before update_resources()")
        model = model if model.name == name else _renamed(model, name)
        compiled = self._runtime.compile_model(model)
        slots = max_concurrency or self.max_executions
        with self._lock:
            self._models[name] = model
            self._compiled[name] = compiled
            self._ctx_pools[name] = make_serving_pool(
                ExecutionContext(compiled, slot_id=i) for i in range(slots))
        act = compiled.activation_size_in_bytes()
        log.info("registered %s: weights=%dB activations~%dB buckets=%s",
                 name, model.weights_size_in_bytes(), act, model.batch_buckets)

    def register_engine(self, name: str, path: str, apply_fn=None,
                        max_concurrency: Optional[int] = None) -> None:
        """Load a serialized engine artifact (reference
        RegisterModel(name, DeserializeEngine(path))).  ``apply_fn`` is
        optional: artifacts with portable modules load without source."""
        if self._allocated:
            raise RuntimeError("register engines before update_resources()")
        compiled = self._runtime.load_engine(path, apply_fn=apply_fn,
                                             model_name=name)
        slots = max_concurrency or self.max_executions
        with self._lock:
            self._models[name] = compiled.model
            self._compiled[name] = compiled
            self._ctx_pools[name] = make_serving_pool(
                ExecutionContext(compiled, slot_id=i) for i in range(slots))

    # -- resource allocation (reference AllocateResources :181-205) ---------
    def update_resources(self, allow_empty: bool = False) -> None:
        """``allow_empty`` permits a manager with no dense models —
        generation-only deployments (Generate RPC engines attach at
        serve() time) need the service plumbing but no staging pools."""
        if not self._models and not allow_empty:
            raise RuntimeError("no models registered")
        # max-reduce staging bytes over models (reference :110-117), with
        # 128KiB headroom per bundle for alignment carve-out
        stack_bytes = max((m.bindings_size_in_bytes()
                           for m in self._models.values()), default=0)
        stack_bytes += 128 * 1024
        from tpulab.tpu.sync import EventPoller
        from tpulab.tpu.transfer import TransferEngine
        self._transfer_engine = TransferEngine()
        self._event_poller = EventPoller()
        # serving pools ride the native futex core when built (cpp/):
        # pool pops park in C without the GIL (reference: the C++ Pool /
        # hybrid_mutex layer IS the reference's hot path, pool.h:454-638)
        self._buffers_pool = make_serving_pool(
            (Buffers(stack_bytes, self.device,
                     transfer_engine=self._transfer_engine,
                     coalesce_h2d=self.coalesce_h2d)
             for _ in range(self.max_buffers)),
            on_return=Buffers.reset)
        self._exec_tokens = make_serving_pool(range(self.max_executions))
        # coalesced H2D parks dispatch threads on put futures — give the
        # stage enough threads that a full transfer cycle can coalesce
        # (capped: parked threads are cheap but not free under the GIL)
        dispatch_threads = (min(16, max(2, self.max_buffers))
                            if self.coalesce_h2d else 2)
        for name, n in (("pre", 2), ("dispatch", dispatch_threads),
                        ("post", 2)):
            if name not in self._thread_pools:
                self._thread_pools[name] = ThreadPool(n, name=name)
        self._allocated = True
        log.info("resources: %d buffer bundles x %dB, %d exec tokens",
                 self.max_buffers, stack_bytes, self.max_executions)

    def register_thread_pool(self, name: str, pool: ThreadPool) -> None:
        """Named pool registry (reference RegisterThreadPool)."""
        self._thread_pools[name] = pool

    def workers(self, name: str) -> ThreadPool:
        return self._thread_pools[name]

    # -- acquisition (blocking; reference :232-273) -------------------------
    def get_buffers(self, timeout: Optional[float] = None) -> PoolItem[Buffers]:
        self._check_allocated()
        return self._buffers_pool.pop(timeout)

    def get_execution_context(self, model_name: str,
                              timeout: Optional[float] = None) -> "ManagedContext":
        """Two-level pop: global token then model slot (reference :254-273)."""
        self._check_allocated()
        token = self._exec_tokens.pop(timeout)
        try:
            ctx = self._ctx_pools[model_name].pop(timeout)
        except BaseException:
            token.release()
            raise
        return ManagedContext(ctx, token)

    # -- introspection ------------------------------------------------------
    @property
    def transfer_engine(self):
        return self._transfer_engine

    @property
    def event_poller(self):
        return self._event_poller

    def model(self, name: str) -> Model:
        return self._models[name]

    def compiled(self, name: str) -> CompiledModel:
        return self._compiled[name]

    @property
    def model_names(self):
        return list(self._models)

    def infer_runner(self, name: str):
        from tpulab.engine.infer_runner import InferRunner
        if name not in self._models:
            raise KeyError(f"model {name!r} is not registered")
        return InferRunner(self, name)

    def _check_allocated(self) -> None:
        if not self._allocated:
            raise RuntimeError("call update_resources() first")

    def shutdown(self) -> None:
        for tp in self._thread_pools.values():
            tp.shutdown()
        if self._transfer_engine is not None:
            self._transfer_engine.shutdown()
        if self._event_poller is not None:
            self._event_poller.shutdown()


class ManagedContext:
    """The two-level (token + context) acquisition handle."""

    def __init__(self, ctx_item: PoolItem[ExecutionContext],
                 token_item: PoolItem[int]):
        self._ctx_item = ctx_item
        self._token_item = token_item

    def get(self) -> ExecutionContext:
        return self._ctx_item.get()

    def release(self) -> None:
        """Return context first, then the global token (reference order)."""
        self._ctx_item.release()
        self._token_item.release()

    def __enter__(self) -> ExecutionContext:
        return self.get()

    def __exit__(self, *exc) -> None:
        self.release()


def _renamed(model: Model, name: str) -> Model:
    return Model(name, model.apply_fn, model.params, model.inputs,
                 model.outputs, model.max_batch_size, model.batch_buckets)
