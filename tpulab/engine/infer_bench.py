"""InferBench: saturating throughput benchmark
(reference infer_bench.h / infer_bench.cc:46-110; result keys :90-98)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np


class InferBench:
    """Timed benchmark loop over a registered model (reference InferBench)."""

    def __init__(self, manager):
        self._mgr = manager

    def run(self, model_name: str, batch_size: int = 1,
            seconds: float = 5.0, warmup: int = 8,
            depth: Optional[int] = None) -> Dict[str, float]:
        """Saturate the pools for ``seconds``; returns the reference's metric
        map: batch_size, max concurrency, batches computed, walltime,
        batches/sec, inf/sec, execution time per batch.

        ``depth`` caps the number of in-flight requests (pipeline depth);
        default = the buffers pool size (full saturation).  Sweeping depth
        is how the dispatch-overlap sweet spot is found (reference
        --contexts/--buffers flag sweep, examples/00)."""
        runner = self._mgr.infer_runner(model_name)
        model = self._mgr.model(model_name)
        inputs = {
            s.name: np.random.default_rng(0).standard_normal(
                s.batched_shape(batch_size)).astype(s.np_dtype)
            for s in model.inputs
        }
        # a full pipeline of slow batches (CPU smoke runs) can legitimately
        # take minutes to drain — scale the per-future timeout with the run
        timeout_s = max(300.0, 60.0 * seconds)
        # warmup: compile-cache everything and fill pipelines
        for _ in range(warmup):
            runner.infer(**inputs).result(timeout=timeout_s)

        inflight: List = []
        max_inflight = depth or self._mgr.max_buffers  # pipeline depth
        batches = 0
        start = time.perf_counter()
        deadline = start + seconds
        while time.perf_counter() < deadline:
            while len(inflight) >= max_inflight:
                inflight.pop(0).result(timeout=timeout_s)
                batches += 1
            inflight.append(runner.infer(**inputs))
        for f in inflight:
            f.result(timeout=timeout_s)
            batches += 1
        walltime = time.perf_counter() - start

        batches_per_sec = batches / walltime
        return {
            "batch_size": batch_size,
            "max_concurrency": float(max_inflight),
            "batches_computed": float(batches),
            "walltime_s": walltime,
            "batches_per_second": batches_per_sec,
            "inferences_per_second": batches_per_sec * batch_size,
            "execution_time_per_batch_ms": 1000.0 / batches_per_sec,
        }

    def latency(self, model_name: str, batch_size: int = 1,
                iterations: int = 100) -> Dict[str, float]:
        """Closed-loop latency percentiles (p50/p90/p99) — the BASELINE.json
        metric definition (not published in the reference repo)."""
        runner = self._mgr.infer_runner(model_name)
        model = self._mgr.model(model_name)
        inputs = {
            s.name: np.zeros(s.batched_shape(batch_size), s.np_dtype)
            for s in model.inputs
        }
        for _ in range(8):
            runner.infer(**inputs).result(timeout=120)
        lats = []
        for _ in range(iterations):
            t0 = time.perf_counter()
            runner.infer(**inputs).result(timeout=120)
            lats.append((time.perf_counter() - t0) * 1000.0)
        arr = np.asarray(lats)
        return {
            "batch_size": batch_size,
            "iterations": iterations,
            "p50_ms": float(np.percentile(arr, 50)),
            "p90_ms": float(np.percentile(arr, 90)),
            "p99_ms": float(np.percentile(arr, 99)),
            "mean_ms": float(arr.mean()),
        }
