"""Speculative decoding: draft-model proposal + single-pass target verify.

Beyond the reference's scope (trtlab predates LLM serving), squarely in
this framework's serving mandate: decode is HBM-bandwidth bound (one
weight read per token), so a small draft model proposes ``k`` tokens and
the target model verifies all of them in ONE chunked forward
(:func:`tpulab.models.transformer.transformer_chunk_step`) — ``a+1``
tokens emitted per target weight-read instead of 1, where ``a`` is the
accepted prefix length.

Greedy acceptance rule: accept draft tokens while they equal the target's
own greedy choice, then emit the target's correction (or bonus) token.
The output is therefore EXACTLY the target model's greedy sequence —
speculation changes latency, never content.  Both KV caches tolerate
rejected-token writes because positions only advance: stale slots are
overwritten before any later step can attend to them (see
transformer_chunk_step's docstring).

.. note:: This module is the LEGACY DENSE path (one session, one
   max_len cache per model).  Production serving speculates inside the
   continuous batcher's fused paged decode blocks instead:
   ``ContinuousBatcher(draft_params=..., draft_n_layers=...)``
   (:mod:`tpulab.engine.paged`) runs draft + verify + accept in one
   device dispatch over the shared paged pool, with adaptive fallback
   to plain blocks.  New integrations should target that path; this one
   stays for the dense Generate-RPC adapter and as the acceptance-rule
   reference.
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional

import numpy as np


class SpeculativeGenerator:
    """Greedy speculative decoding over two transformer-family models."""

    def __init__(self, target_params: Any, draft_params: Any, *,
                 n_heads: int, n_layers: int,
                 draft_n_heads: Optional[int] = None,
                 draft_n_layers: Optional[int] = None,
                 k: int = 4, max_len: int = 1024,
                 compute_dtype=None, device=None,
                 n_kv_heads: Optional[int] = None,
                 draft_n_kv_heads: Optional[int] = None,
                 rope_theta: Optional[float] = None):
        import jax
        import jax.numpy as jnp

        from tpulab.models.transformer import (init_kv_cache,
                                               transformer_chunk_step,
                                               transformer_decode_step)
        from tpulab.tpu import platform as plat

        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self.max_len = max_len
        self.device = device if device is not None else plat.local_device(0)
        cdt = compute_dtype or jnp.float32
        self._jnp = jnp
        #: id-validation bound (public: the Generate RPC checks it)
        self.vocab = int(target_params["embed"].shape[0])
        self.target_params = jax.device_put(target_params, self.device)
        self.draft_params = jax.device_put(draft_params, self.device)

        dh = draft_n_heads or n_heads
        dl = draft_n_layers or n_layers
        t_kv = n_kv_heads or n_heads
        # same-arch draft (draft_n_heads omitted) inherits the target's KV
        # head count; an explicit draft arch defaults to MHA
        d_kv = draft_n_kv_heads or (t_kv if draft_n_heads is None else dh)
        t_dim = target_params["embed"].shape[1] // n_heads
        d_dim = draft_params["embed"].shape[1] // dh
        self._t_cache = partial(init_kv_cache, 1, max_len, n_layers, t_kv,
                                t_dim, cdt)
        self._d_cache = partial(init_kv_cache, 1, max_len, dl, d_kv,
                                d_dim, cdt)
        # target: one chunked forward verifies a whole proposal window
        # (M = k+1 fixed -> one compiled program; prefill buckets by pow2)
        self._verify = jax.jit(partial(
            transformer_chunk_step, n_heads=n_heads, n_layers=n_layers,
            compute_dtype=cdt, n_kv_heads=n_kv_heads, rope_theta=rope_theta))
        # draft: chunked prefill + k single-token steps under one jitted scan
        self._d_prefill = jax.jit(partial(
            transformer_chunk_step, n_heads=dh, n_layers=dl,
            compute_dtype=cdt, n_kv_heads=d_kv,
            rope_theta=rope_theta))
        d_step = partial(transformer_decode_step, n_heads=dh, n_layers=dl,
                         compute_dtype=cdt, n_kv_heads=d_kv,
                         rope_theta=rope_theta)

        @jax.jit
        def draft_propose(params, cache, tok, pos0):
            # k+1 iterations: the extra one FEEDS drafts[k-1] so its K/V
            # lands in the draft cache (a fully-accepted round advances
            # past position pos0+k — without this the slot would stay a
            # zero hole every later draft query attends).  Its output is
            # discarded; on partial acceptance the extra writes are stale
            # but positions only advance, so they are overwritten before
            # they become visible.
            def body(carry, i):
                cache, tok = carry
                logits, cache = d_step(params, cache, tok, pos0 + i)
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return (cache, nxt), nxt[0]
            (cache, _), toks = jax.lax.scan(body, (cache, tok),
                                            jnp.arange(self.k + 1))
            return toks[:self.k], cache
        self._propose = draft_propose

    # -- public --------------------------------------------------------------
    def stream(self, prompt, steps: int):
        """Yield exactly ``steps`` greedy tokens as they are VERIFIED —
        one burst per speculation round (accepted prefix + correction).
        Tokens never stream before the target has verified them, so a
        consumer sees the same exactly-greedy sequence ``generate``
        returns, with burst granularity.  Each call owns fresh KV caches
        (concurrent streams on one instance are safe; the jitted
        programs are shared).  ``rounds``/``accepted`` telemetry from the
        last finished call is exposed on the instance."""
        # validate EAGERLY (at call time, not first iteration): direct
        # stream() callers get the ValueError before they start consuming
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size and (prompt.min() < 0 or prompt.max() >= self.vocab):
            # XLA gather CLAMPS out-of-bounds ids — silent garbage; reject
            # at the host boundary, mirroring ContinuousBatcher.submit
            # (ADVICE r5: direct library callers, not just the RPC)
            raise ValueError(f"prompt token ids outside [0, {self.vocab})")
        t_p = prompt.shape[0]
        if max(t_p + steps + self.k + 1,
               1 << (t_p - 1).bit_length()) > self.max_len:
            raise ValueError("prompt+steps+k exceeds max_len")
        if steps <= 0:  # exactly-steps contract holds at zero too
            self.rounds = self.accepted = 0
            return iter(())
        return self._stream_impl(prompt, t_p, steps)

    def _stream_impl(self, prompt, t_p: int, steps: int):
        jnp = self._jnp
        t_cache, d_cache = self._t_cache(), self._d_cache()
        # prefill both models with one chunked forward each (pow2 bucket)
        t_pad = 1 << (t_p - 1).bit_length()
        padded = np.zeros((1, t_pad), np.int32)
        padded[0, :t_p] = prompt
        tl, t_cache = self._verify(self.target_params, t_cache,
                                   jnp.asarray(padded), jnp.int32(0))
        _, d_cache = self._d_prefill(self.draft_params, d_cache,
                                     jnp.asarray(padded), jnp.int32(0))
        cur = int(np.asarray(tl)[0, t_p - 1].argmax())
        emitted_n = 1
        yield cur
        p = t_p                     # tokens FED to the target so far
        rounds = accepted = 0
        while emitted_n < steps:
            drafts, d_cache = self._propose(
                self.draft_params, d_cache,
                jnp.asarray([cur], jnp.int32), jnp.int32(p))
            drafts = np.asarray(drafts, np.int32)          # (k,)
            chunk = np.concatenate([[cur], drafts])[None, :]  # (1, k+1)
            logits, t_cache = self._verify(
                self.target_params, t_cache, jnp.asarray(chunk),
                jnp.int32(p))
            greedy = np.asarray(logits)[0].argmax(-1).astype(np.int32)
            # accept the agreeing prefix; token a's correction (or the
            # bonus after a full match) is always emitted
            a = 0
            while a < self.k and drafts[a] == greedy[a]:
                a += 1
            cur = int(greedy[a])
            p += a + 1
            rounds += 1
            accepted += a
            for tok in list(drafts[:a]) + [cur]:
                if emitted_n < steps:
                    emitted_n += 1
                    yield int(tok)
        self.rounds = rounds
        self.accepted = accepted

    def generate(self, prompt, steps: int) -> List[int]:
        """Greedy-decode ``steps`` tokens; returns exactly the target
        model's greedy continuation (see :meth:`stream`)."""
        return list(self.stream(prompt, steps))


# canonical home: tpulab.models.transformer (draft-param plumbing shared
# with the paged speculative path); re-exported here for existing callers
from tpulab.models.transformer import early_exit_draft  # noqa: E402,F401


def benchmark_speculative(n_heads: int = 8, n_layers: int = 8,
                          d_model: int = 512, d_ff: int = 2048,
                          vocab: int = 2048, draft_layers: int = 2,
                          k: int = 4, steps: int = 128,
                          prompt_len: int = 16, max_len: int = 512,
                          compute_dtype=None, seed: int = 0,
                          tail_scale: float = 0.05):
    """Acceptance rate + tok/s of speculative vs plain greedy decode
    (VERDICT r4 #7: 'a number, not a feature flag').

    Capture-wise superseded by the serving-path ``speculative_decode``
    row (:func:`tpulab.engine.paged.benchmark_speculative_decode`),
    which runs spec and plain through ONE ContinuousBatcher workload —
    no duplicated plain-baseline loop.  This dense-path variant stays as
    the acceptance-mechanics microbenchmark.

    Weights are synthetic, so ``tail_scale`` shrinks the output
    projections of layers past the draft exit: in a *trained* model the
    late layers refine the residual stream rather than overturn it (the
    property early-exit speculation exploits); raw random layers instead
    flip the argmax of near-uniform logits on every token (acceptance
    pins to 0 and the row measures nothing).  The resulting acceptance
    is an emulation — real-checkpoint acceptance depends on the model —
    but the tok/s-at-acceptance mechanics and the exactness guarantee
    are the real measurement.

    Plain decode is measured serving-shaped — a host loop over one jitted
    decode step, exactly how the generation engine streams tokens — so
    both sides carry the same per-token host overhead.
    """
    import time

    import jax
    import jax.numpy as jnp

    from tpulab.models.transformer import (init_kv_cache,
                                           init_transformer_params,
                                           transformer_chunk_step,
                                           transformer_decode_step)

    target = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=d_ff, seed=seed)
    for i in range(draft_layers, n_layers):  # see tail_scale docstring
        for w in ("wo", "w2"):
            target[f"layer{i}"][w] = target[f"layer{i}"][w] * tail_scale
    draft = early_exit_draft(target, draft_layers)
    spec = SpeculativeGenerator(
        target, draft, n_heads=n_heads, n_layers=n_layers,
        draft_n_heads=n_heads, draft_n_layers=draft_layers, k=k,
        max_len=max_len, compute_dtype=compute_dtype)
    rng = np.random.default_rng(seed)
    prompt = rng.integers(0, vocab, (prompt_len,)).astype(np.int32)

    spec.generate(prompt, 8)  # compile + warm both programs
    t0 = time.perf_counter()
    spec_toks = spec.generate(prompt, steps)
    spec_s = time.perf_counter() - t0
    acceptance = spec.accepted / max(spec.rounds * k, 1)

    # plain greedy: host loop over one jitted single-token step (the
    # serving shape), identical prefill
    from functools import partial as _partial
    cdt = compute_dtype or jnp.float32
    head_dim = d_model // n_heads
    prefill = jax.jit(_partial(transformer_chunk_step, n_heads=n_heads,
                               n_layers=n_layers, compute_dtype=cdt))
    step = jax.jit(_partial(transformer_decode_step, n_heads=n_heads,
                            n_layers=n_layers, compute_dtype=cdt))

    def plain(n: int) -> List[int]:
        cache = init_kv_cache(1, max_len, n_layers, n_heads, head_dim, cdt)
        t_pad = 1 << (prompt_len - 1).bit_length()
        padded = np.zeros((1, t_pad), np.int32)
        padded[0, :prompt_len] = prompt
        logits, cache = prefill(target, cache, jnp.asarray(padded),
                                jnp.int32(0))
        cur = int(np.asarray(logits)[0, prompt_len - 1].argmax())
        out = [cur]
        pos = prompt_len
        while len(out) < n:
            lg, cache = step(target, cache,
                             jnp.asarray([cur], jnp.int32), jnp.int32(pos))
            cur = int(np.asarray(lg)[0].argmax())
            out.append(cur)
            pos += 1
        return out

    plain(8)  # warm
    t0 = time.perf_counter()
    plain_toks = plain(steps)
    plain_s = time.perf_counter() - t0

    return {"k": k, "draft_layers": draft_layers, "n_layers": n_layers,
            "steps": steps,
            "acceptance": round(acceptance, 3),
            "rounds": spec.rounds,
            "spec_tok_s": round(steps / spec_s, 1),
            "plain_tok_s": round(steps / plain_s, 1),
            "speedup": round(plain_s / spec_s, 3),
            "exact_match": bool(spec_toks == plain_toks)}


class _SpeculativeSession:
    """One admitted decode: usable directly (``close()``) or as a context
    manager, mirroring the dense :class:`GenerationSession` shape.  The
    semaphore slot releases exactly once — on close/exit or, as a last
    resort, at GC, so an abandoned session cannot deadlock admission."""

    def __init__(self, spec: SpeculativeGenerator, sem, on_close=None):
        self._spec = spec
        self._sem = sem
        self._on_close = on_close
        self._prompt: Optional[np.ndarray] = None
        self._completed = False
        self._served = 0
        self._errored = False
        self._closed = False

    def prefill(self, prompt) -> None:
        if self._closed:
            raise RuntimeError("session is closed")
        self._prompt = np.asarray(prompt, np.int32).reshape(-1)

    def stream(self, steps: int, deadline=None):
        if self._closed:
            raise RuntimeError("session is closed")
        if self._prompt is None:
            raise RuntimeError("prefill() before stream()")
        inner = self._spec.stream(self._prompt, steps)
        if deadline is not None:
            # deadline checks ride the burst boundaries: verified tokens
            # already computed still stream, the NEXT round is what stops
            inner = self._deadlined(inner, deadline)

        def counted():
            # a session completes when its stream is EXHAUSTED, or when
            # the consumer closes it early after >=1 served token (the
            # stop-token break path).  The served count lives on the
            # session (updated per token) rather than in a GeneratorExit
            # handler, so completion does not depend on the generator
            # being finalized before close() runs (refcount ordering is
            # a CPython detail).  Errors flag the session instead —
            # close() must NOT count an errored stream, mirroring
            # ContinuousBatcher.completed_requests (success-only)
            try:
                for tok in inner:
                    self._served += 1
                    yield tok
            except GeneratorExit:   # early close by the consumer: no error
                raise
            except BaseException:
                self._errored = True
                raise
            self._completed = True

        return counted()

    @staticmethod
    def _deadlined(inner, deadline):
        # check BEFORE pulling the next round, so already-verified tokens
        # still reach the consumer and no compute starts past expiry
        while True:
            deadline.check("generation")
            try:
                tok = next(inner)
            except StopIteration:
                return
            yield tok

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._sem.release()
            if ((self._completed or (self._served > 0
                                     and not self._errored))
                    and self._on_close is not None):
                self._on_close()

    def __enter__(self) -> "_SpeculativeSession":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def __del__(self):  # GC fallback; close() is idempotent
        self.close()


class SpeculativeSessionEngine:
    """Serving adapter: a :class:`SpeculativeGenerator` behind the
    Generate RPC's dense-session interface (``start_session`` ->
    ``prefill``/``stream``), so speculative decoding plugs into
    ``manager.serve(generation_engines={...})`` like any engine.

    Tokens stream in verified bursts (one per speculation round); the
    wire sequence is exactly the target model's greedy output.  Sessions
    are admission tokens (``max_sessions`` bounds concurrent decodes —
    the generator itself is stateless per call); sampling requests are
    rejected upstream by the dense-path greedy-only check.

    .. deprecated:: PR 7
       The batcher path supersedes this adapter for serving: speculation
       now runs inside the fused paged decode blocks
       (``ContinuousBatcher(draft_params=...)``), which batches lanes,
       shares the paged pool, supports device sampling, and degrades
       adaptively — serve through the batcher and keep this adapter only
       for the single-session dense contract."""

    def __init__(self, spec: SpeculativeGenerator, max_sessions: int = 2):
        import threading
        self._spec = spec
        self._sem = threading.BoundedSemaphore(max_sessions)
        self._count_lock = threading.Lock()
        #: sessions that streamed and closed (oneshot/ops accounting,
        #: mirroring ContinuousBatcher.completed_requests)
        self.completed_requests = 0

    def _count_completion(self) -> None:
        with self._count_lock:
            self.completed_requests += 1

    @property
    def vocab(self):
        return self._spec.vocab

    #: telemetry passthrough (last finished call)
    @property
    def rounds(self):
        return getattr(self._spec, "rounds", 0)

    @property
    def accepted(self):
        return getattr(self._spec, "accepted", 0)

    def start_session(self, timeout: Optional[float] = None
                      ) -> _SpeculativeSession:
        if not self._sem.acquire(timeout=timeout):
            raise TimeoutError("no speculative session available")
        return _SpeculativeSession(self._spec, self._sem,
                                   on_close=self._count_completion)
