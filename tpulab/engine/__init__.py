"""tpulab.engine — the executable runtime (reference trtlab/tensorrt, §2.5).

The reference's object model, re-grounded on XLA:

| reference (TensorRT)                  | tpulab (XLA/PjRt)                     |
|---------------------------------------|---------------------------------------|
| serialized engine "plan" file         | engine artifact: StableHLO + params + |
|                                       | per-bucket serialized executables     |
| Runtime::deserialize_engine           | Runtime.load_engine / compile_model   |
| optimization profiles (min/opt/max)   | batch buckets (1,2,4,...,max), padded |
| ICudaEngine introspection             | Model binding specs + memory_analysis |
| IExecutionContext w/o device memory   | ExecutionContext (execution slot;     |
|                                       | scratch is XLA-managed, slot-pooled)  |
| cudaGraph capture + graphLaunch       | jit-compiled program (XLA compiles    |
|                                       | the whole graph; dispatch is a single |
|                                       | pre-compiled call)                    |
| InferenceManager pools                | InferenceManager pools (same design)  |
| Bindings/Buffers host+device stacks   | Bindings over pinned staging views +  |
|                                       | device arrays                         |
| InferRunner 3-stage pre/cuda/post     | InferRunner 3-stage pre/dispatch/post |
| InferBench                            | InferBench                            |
"""

from tpulab.engine.model import IOSpec, Model, default_batch_buckets
from tpulab.engine.runtime import Runtime, CompiledModel
from tpulab.engine.execution_context import ExecutionContext
from tpulab.engine.buffers import Buffers, Bindings
from tpulab.engine.inference_manager import InferenceManager
from tpulab.engine.infer_runner import InferRunner
from tpulab.engine.infer_bench import InferBench
from tpulab.engine.workspace import (
    StaticSingleModelGraphWorkspace,
    BenchmarkWorkspace,
    TimedBenchmarkWorkspace,
)

__all__ = [
    "IOSpec", "Model", "default_batch_buckets",
    "Runtime", "CompiledModel",
    "ExecutionContext",
    "Buffers", "Bindings",
    "InferenceManager", "InferRunner", "InferBench",
    "StaticSingleModelGraphWorkspace", "BenchmarkWorkspace",
    "TimedBenchmarkWorkspace",
]
