"""Workspaces: pre-bound single-model execution
(reference workspace.h:29-106, workspace.cc:44-164).

- ``StaticSingleModelGraphWorkspace`` — everything pre-bound and warmed; each
  ``enqueue()`` is a single pre-compiled dispatch.  The reference captures
  enqueueV2 into a cudaGraph to erase launch overhead (workspace.cc:61-76);
  XLA's compiled program plays that role natively: the whole model is one
  fused graph, dispatched with one call.
- ``BenchmarkWorkspace`` — adds pinned host mirrors + async H2D/D2H
  (workspace.cc:90-124).
- ``TimedBenchmarkWorkspace`` — per-stage timing of H2D / compute / D2H
  (workspace.cc:126-164 cudaEvent timing -> monotonic timing around
  blocking syncs).
"""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from tpulab.engine.model import Model
from tpulab.engine.runtime import CompiledModel, Runtime
from tpulab.tpu.copy import copy_to_device, copy_to_host
from tpulab.tpu.sync import tpu_sync_standard


class StaticSingleModelGraphWorkspace:
    """Pre-bound, warmed, fixed-batch workspace (reference
    StaticSingleModelGraphWorkspace)."""

    def __init__(self, model: Model, batch_size: int = 0, device=None,
                 compiled: CompiledModel = None):
        self.batch_size = batch_size or model.max_batch_size
        self.bucket = model.pick_bucket(self.batch_size)
        self.model = model
        self._compiled = compiled or Runtime(device).compile_model(
            model, buckets=[self.bucket])
        self.device = self._compiled.device
        # pre-bound device inputs (the graph's fixed bindings)
        self.device_inputs: Dict[str, Any] = {
            s.name: copy_to_device(
                np.zeros(s.batched_shape(self.bucket), s.np_dtype), self.device)
            for s in model.inputs
        }
        self.device_outputs: Dict[str, Any] = {}
        self.warmup()

    def warmup(self) -> None:
        """One throwaway dispatch (reference workspace.cc warmup before
        graph capture)."""
        out = self._compiled(self.bucket, self.device_inputs)
        tpu_sync_standard(out)

    def enqueue(self) -> Dict[str, Any]:
        """Async dispatch on current device inputs (the graphLaunch analog)."""
        self.device_outputs = self._compiled(self.bucket, self.device_inputs)
        return self.device_outputs

    def synchronize(self) -> None:
        tpu_sync_standard(self.device_outputs)


class BenchmarkWorkspace(StaticSingleModelGraphWorkspace):
    """Adds pinned host mirrors + explicit async H2D/D2H
    (reference BenchmarkWorkspace)."""

    def __init__(self, model: Model, batch_size: int = 0, device=None,
                 compiled: CompiledModel = None):
        super().__init__(model, batch_size, device, compiled)
        from tpulab.tpu.allocators import make_staging_allocator
        from tpulab.memory.allocator import make_allocator
        alloc = make_allocator(make_staging_allocator())
        self._host_desc = []
        self.host_inputs: Dict[str, np.ndarray] = {}
        self.host_outputs: Dict[str, np.ndarray] = {}
        for s in model.inputs:
            d = alloc.allocate_descriptor(s.bytes_per_sample() * self.bucket)
            self._host_desc.append(d)
            self.host_inputs[s.name] = d.numpy(s.np_dtype,
                                               s.batched_shape(self.bucket))
        for s in model.outputs:
            d = alloc.allocate_descriptor(s.bytes_per_sample() * self.bucket)
            self._host_desc.append(d)
            self.host_outputs[s.name] = d.numpy(s.np_dtype,
                                                s.batched_shape(self.bucket))

    def async_h2d(self) -> None:
        for name, host in self.host_inputs.items():
            self.device_inputs[name] = copy_to_device(host, self.device)

    def async_d2h(self) -> None:
        for name, dev in self.device_outputs.items():
            if name in self.host_outputs:
                copy_to_host(dev, self.host_outputs[name])

    def run(self) -> None:
        self.async_h2d()
        self.enqueue()
        self.async_d2h()


class TimedBenchmarkWorkspace(BenchmarkWorkspace):
    """Per-stage timings (reference TimedBenchmarkWorkspace cudaEvents)."""

    def timed_run(self) -> Dict[str, float]:
        t0 = time.perf_counter()
        self.async_h2d()
        tpu_sync_standard(self.device_inputs)
        t1 = time.perf_counter()
        self.enqueue()
        tpu_sync_standard(self.device_outputs)
        t2 = time.perf_counter()
        self.async_d2h()
        t3 = time.perf_counter()
        return {
            "h2d_ms": (t1 - t0) * 1e3,
            "compute_ms": (t2 - t1) * 1e3,
            "d2h_ms": (t3 - t2) * 1e3,
            "total_ms": (t3 - t0) * 1e3,
        }
