"""BatchedInferRunner: server-side dynamic batching.

The reference ships dynamic batching as a *front service* (examples/03's
unary->stream forwarder + Deployment/batcher.cc) over the core
StandardBatcher/Dispatcher.  Here it is also a first-class runner: N
concurrent ``infer`` calls aggregate into one device batch — one staging
fill, one H2D, one compiled dispatch, one D2H for the whole group — then
split back per caller.

Works over any inner runner exposing ``infer(**arrays) -> Future`` — the
local :class:`~tpulab.engine.infer_runner.InferRunner` or a remote runner
(the examples/03 middleman builds on the remote form via
:meth:`BatchedInferRunner.over_runner`).

On TPU this is the decisive serving lever: per-dispatch and per-transfer
fixed costs amortize across the group, and the bucketed batch programs stay
hot.  Latency bound follows the reference's formula (examples/03/README:23-25):
``window + batchN_compute - batch1_compute``.

Why this does not wrap core.Dispatcher: the core batcher counts *items*
(one promise per batch), while request aggregation must account *rows*
(requests carry batch dims, overflow must flush-then-open, and every caller
needs its own sliced future).  The window/seq machinery is intentionally the
same shape so the two stay reviewable side by side.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from tpulab.core.task_pool import DeferredShortTaskPool
from tpulab.core.thread_pool import ThreadPool


class BatchedInferRunner:
    """Aggregating runner over an inner ``infer(**arrays)`` runner."""

    def __init__(self, manager, model_name: str,
                 window_s: float = 0.002,
                 max_batch_size: Optional[int] = None):
        model = manager.model(model_name)
        # window launches get a DEDICATED pool: sharing the manager's "pre"
        # pool deadlocks when callers (e.g. StreamInfer handlers) block on
        # batch futures from those same workers
        self._init(inner=manager.infer_runner(model_name),
                   input_names=[s.name for s in model.inputs],
                   window_s=window_s,
                   max_batch_size=max_batch_size or model.max_batch_size,
                   launch_workers=None)
        self.model = model
        self.model_name = model_name

    @classmethod
    def over_runner(cls, inner, input_names: Sequence[str],
                    max_batch_size: int, window_s: float = 0.002,
                    launch_workers: Optional[ThreadPool] = None
                    ) -> "BatchedInferRunner":
        """Aggregate over any runner (e.g. a RemoteInferenceManager runner —
        the examples/03 middleman shape)."""
        self = cls.__new__(cls)
        self._init(inner, list(input_names), window_s, max_batch_size,
                   launch_workers)
        self.model = None
        self.model_name = None
        return self

    def _init(self, inner, input_names: List[str], window_s: float,
              max_batch_size: int, launch_workers: Optional[ThreadPool]):
        self._inner = inner
        self._input_names = input_names
        self.window_s = window_s
        self.max_batch_size = max_batch_size
        self._lock = threading.Lock()
        self._open: List[dict] = []       # items: {arrays, n, future}
        self._open_rows = 0
        self._batch_seq = 0
        self._timers = DeferredShortTaskPool(name="batch-window")
        # launches may block (buffer-pool backpressure) — they must never run
        # on the timer thread (its tasks must stay short)
        self._own_workers = launch_workers is None
        self._workers = launch_workers or ThreadPool(2, name="batch-launch")
        import inspect
        try:
            self._has_post_fn = "post_fn" in inspect.signature(
                inner.infer).parameters
        except (TypeError, ValueError):  # pragma: no cover
            self._has_post_fn = False
        #: compute seconds of the most recent device batch (metrics hook)
        self.last_compute_s: Optional[float] = None

    # -- public -------------------------------------------------------------
    def infer(self, **arrays: np.ndarray) -> Future:
        """Enqueue one request; resolves to its own dict of outputs."""
        if not arrays:
            raise ValueError("no input arrays")
        n = next(iter(arrays.values())).shape[0]
        if n > self.max_batch_size:
            # oversized requests bypass aggregation
            return self._inner.infer(**arrays)
        import time as _time
        item = {"arrays": arrays, "n": n, "future": Future(),
                "t0": _time.perf_counter()}
        groups: List[List[dict]] = []
        with self._lock:
            if self._open_rows + n > self.max_batch_size:
                groups.append(self._close_locked())   # flush what's open
            self._open.append(item)
            self._open_rows += n
            seq = self._batch_seq
            if self._open_rows >= self.max_batch_size:
                groups.append(self._close_locked())   # closed by size
            # arm the window timer iff this item opened a fresh batch that
            # is still waiting for more rows
            needs_timer = bool(self._open) and self._open[0] is item
        for group in groups:
            self._launch(group)
        if needs_timer:
            self._timers.enqueue_deferred(
                self.window_s, lambda: self._window_fired(seq))
        return item["future"]

    def flush(self) -> None:
        with self._lock:
            group = self._close_locked()
        if group:
            self._launch(group)

    def shutdown(self) -> None:
        self.flush()
        self._timers.shutdown()
        if self._own_workers:
            self._workers.shutdown()

    # -- internals ----------------------------------------------------------
    def _close_locked(self) -> List[dict]:
        group, self._open = self._open, []
        self._open_rows = 0
        self._batch_seq += 1
        return group

    def _window_fired(self, seq: int) -> None:
        with self._lock:
            if self._batch_seq != seq:   # closed by size already
                return
            group = self._close_locked()
        if group:
            # hand off: _launch may block on pool backpressure
            self._workers.enqueue(self._launch, group)

    def _launch(self, group: List[dict]) -> None:
        if not group:
            return
        import time as _time
        t_launch = _time.perf_counter()
        for it in group:
            # aggregation wait (enqueue -> launch): the window + any
            # size-close delay, exported per request for stage profiling
            it["future"]._tpulab_queue_s = t_launch - it["t0"]
        try:
            combined = {
                name: np.concatenate([it["arrays"][name] for it in group],
                                     axis=0)
                for name in self._input_names
            }
            offsets = np.cumsum([0] + [it["n"] for it in group])
            if self._has_post_fn:
                fut = self._inner.infer(
                    post_fn=self._make_split(group, offsets), **combined)
            else:
                fut = self._inner.infer(**combined)
        except BaseException as e:  # noqa: BLE001 - fail the WHOLE group
            for it in group:
                if not it["future"].done():
                    it["future"].set_exception(e)
            return

        def _settle(f):
            exc = f.exception()
            if exc is not None:
                for it in group:
                    if not it["future"].done():
                        it["future"].set_exception(exc)
            elif not self._has_post_fn:
                # remote runners resolve to an outputs dict directly
                outs = f.result()
                for i, it in enumerate(group):
                    lo, hi = offsets[i], offsets[i + 1]
                    if not it["future"].done():
                        it["future"].set_result(
                            {k: v[lo:hi] for k, v in outs.items()})
        fut.add_done_callback(_settle)

    def _make_split(self, group: List[dict], offsets):
        def split(bindings):
            cs = getattr(bindings, "compute_seconds", None)
            self.last_compute_s = cs
            outs = bindings.outputs()
            for i, it in enumerate(group):
                lo, hi = offsets[i], offsets[i + 1]
                if not it["future"].done():
                    it["future"]._tpulab_compute_s = cs  # per-request timing
                    it["future"].set_result(
                        {k: v[lo:hi].copy() for k, v in outs.items()})
        return lambda b: (split(b), None)[1]
