"""Runtime: compiles/loads models into per-bucket XLA executables
(reference runtime.h:43-110, runtime.cc — deserialize_engine with logger
bridge + weight capture).

The TPU "engine artifact" (the TRT plan-file analog) is a directory:

    <path>/spec.json            IO contract, buckets, model name
    <path>/params.npz           weight leaves (flattened pytree)
    <path>/treedef.pkl          pytree structure
    <path>/bucket_<N>.xla       serialized compiled executable (optional,
                                topology-specific; recompiled if unusable)
    <path>/bucket_<N>.shlo      portable jax.export StableHLO module per
                                bucket — loads WITHOUT the original Python
                                apply_fn (the TRT property that a plan file
                                carries the network; per-platform, like a
                                plan file is per-GPU-arch)

``CompiledModel`` owns the per-bucket compiled programs for one device — the
compiled program *is* the cudaGraph analog: one pre-compiled dispatch per
bucket, no per-call graph building.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
from typing import Any, Dict, Optional, Sequence

import numpy as np

from tpulab.engine.model import IOSpec, Model
from tpulab.tpu import platform as plat

log = logging.getLogger("tpulab.engine")


class CompiledModel:
    """Per-device compiled executables, one per batch bucket."""

    def __init__(self, model: Model, device, executables: Dict[int, Any],
                 device_params: Any, allocator=None, weights_addr=None):
        self.model = model
        self.device = device
        self.executables = executables      # bucket -> jax Compiled
        self.device_params = device_params  # params resident on `device`
        #: the device allocator that placed the weights + their block addr
        #: (reference: Model owns captured weight pointers, runtime.cc:134)
        self.allocator = allocator
        self.weights_addr = weights_addr

    def release_weights(self) -> None:
        """Eagerly free the weights' HBM via the owning allocator."""
        if self.allocator is not None and self.weights_addr is not None:
            self.allocator.deallocate_node(self.weights_addr)
            self.weights_addr = None
            self.device_params = None

    def memory_analysis(self, bucket: Optional[int] = None):
        """Activation/scratch sizing (the TRT getDeviceMemorySize analog)."""
        b = bucket or self.model.batch_buckets[-1]
        try:
            return self.executables[b].memory_analysis()
        except Exception:  # backend may not support it (CPU tests)
            return None

    def activation_size_in_bytes(self) -> int:
        ma = self.memory_analysis()
        if ma is None:
            return 0
        return int(getattr(ma, "temp_size_in_bytes", 0) +
                   getattr(ma, "output_size_in_bytes", 0))

    def flops(self, bucket: Optional[int] = None) -> Optional[float]:
        """XLA cost-analysis FLOPs of one bucket's executable (the whole
        batch, not per-row) — the MFU numerator.  None when the backend
        doesn't report cost analysis."""
        b = bucket or self.model.batch_buckets[-1]
        try:
            ca = self.executables[b].cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax: one per device
                ca = ca[0]
            f = float(ca["flops"])
            return f if f > 0 else None
        except Exception:
            return None

    def __call__(self, bucket: int, inputs: Dict[str, Any]) -> Dict[str, Any]:
        return self.executables[bucket](self.device_params, inputs)


class Runtime:
    """Model compiler/loader (reference Runtime/CustomRuntime).

    The reference's allocator-capture trick (ManagedRuntime unified-memory
    weights) has no PjRt analog — weights live in HBM owned by the runtime;
    HBM headroom is tracked via DeviceInfo.memory_info instead (SURVEY §7
    risk note).
    """

    def __init__(self, device=None):
        from tpulab.tpu.allocators import make_tpu_allocator
        self.device = device if device is not None else plat.local_device(0)
        #: installed device allocator (reference CustomRuntime installing an
        #: NvAllocator, runtime.h:81-110) — weights are captured through it
        self.allocator = make_tpu_allocator(self.device)

    # -- compile ------------------------------------------------------------
    def compile_model(self, model: Model, buckets: Optional[Sequence[int]] = None,
                      donate_params: bool = False,
                      _placed: Optional[tuple] = None) -> CompiledModel:
        """JIT-compile one executable per batch bucket (AOT, warmed)."""
        import jax

        buckets = sorted(buckets or model.batch_buckets)
        # weight capture: the allocator records the placement so the
        # CompiledModel owns its weight bytes (tracked HBM); ``_placed``
        # reuses a capture already made (load_engine's fallback compile)
        owns_placement = _placed is None
        weights_addr, device_params = (
            _placed if _placed is not None
            else self.allocator.allocate_tree(model.params))
        try:
            return self._compile_buckets(model, buckets, weights_addr,
                                         device_params)
        except BaseException:
            if owns_placement:
                # a failed compile must not pin a weight copy in the
                # long-lived allocator (each retry would leak a full tree)
                self.allocator.deallocate_node(weights_addr)
            raise

    def _compile_buckets(self, model: Model, buckets, weights_addr,
                         device_params) -> CompiledModel:
        import jax

        def call(params, inputs):
            return model.apply_fn(params, inputs)

        # Pin the lowering to this Runtime's device: without explicit
        # shardings AOT executables bind to the default device and reject
        # arguments committed elsewhere (multi-chip managers).
        from jax.sharding import SingleDeviceSharding
        dev_sharding = SingleDeviceSharding(self.device)
        executables: Dict[int, Any] = {}
        for b in buckets:
            dummy = {
                s.name: jax.ShapeDtypeStruct(s.batched_shape(b), s.np_dtype,
                                             sharding=dev_sharding)
                for s in model.inputs
            }
            pspec = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=dev_sharding),
                device_params)
            lowered = jax.jit(call).lower(pspec, dummy)
            executables[b] = lowered.compile()
            log.info("compiled %s bucket=%d", model.name, b)
        return CompiledModel(model, self.device, executables, device_params,
                             allocator=self.allocator,
                             weights_addr=weights_addr)

    # -- engine artifacts ----------------------------------------------------
    def save_engine(self, compiled: CompiledModel, path: str) -> None:
        """Serialize an engine artifact (the TRT plan-file analog)."""
        import jax

        os.makedirs(path, exist_ok=True)
        model = compiled.model
        spec = {
            "name": model.name,
            "max_batch_size": model.max_batch_size,
            "batch_buckets": model.batch_buckets,
            "inputs": [[s.name, list(s.shape), np.dtype(s.dtype).name]
                       for s in model.inputs],
            "outputs": [[s.name, list(s.shape), np.dtype(s.dtype).name]
                        for s in model.outputs],
        }
        with open(os.path.join(path, "spec.json"), "w") as f:
            json.dump(spec, f, indent=2)
        leaves, treedef = jax.tree_util.tree_flatten(model.params)
        np.savez(os.path.join(path, "params.npz"),
                 **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)})
        with open(os.path.join(path, "treedef.pkl"), "wb") as f:
            pickle.dump(jax.tree_util.tree_structure(model.params), f)
        for b, exe in compiled.executables.items():
            try:
                from jax.experimental import serialize_executable as se
                blob, in_tree, out_tree = se.serialize(exe)
                with open(os.path.join(path, f"bucket_{b}.xla"), "wb") as f:
                    pickle.dump((blob, in_tree, out_tree), f)
            except Exception as e:  # serialization is an optimization only
                log.warning("executable serialization unavailable (%s); "
                            "artifact will recompile on load", e)
        # portable program: jax.export StableHLO per bucket — the part of
        # the artifact that reloads without the Python source (TRT plan
        # files carry the network; so do we)
        try:
            self._save_exported(compiled, path)
        except Exception as e:
            log.warning("portable StableHLO export unavailable (%s); "
                        "artifact will need apply_fn to load", e)

    def _save_exported(self, compiled: CompiledModel, path: str) -> None:
        import jax
        from jax import export as jexport

        model = compiled.model

        def call(params, inputs):
            return model.apply_fn(params, inputs)

        pspec = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            compiled.device_params)
        for b in model.batch_buckets:
            dummy = {
                s.name: jax.ShapeDtypeStruct(s.batched_shape(b), s.np_dtype)
                for s in model.inputs
            }
            exported = jexport.export(jax.jit(call))(pspec, dummy)
            with open(os.path.join(path, f"bucket_{b}.shlo"), "wb") as f:
                f.write(exported.serialize())

    def load_engine(self, path: str,
                    apply_fn=None, model_name: Optional[str] = None) -> CompiledModel:
        """Load an engine artifact; reuses serialized executables when the
        topology matches, else recompiles from ``apply_fn``
        (reference deserialize_engine, runtime.cc:62-95)."""
        import jax

        with open(os.path.join(path, "spec.json")) as f:
            spec = json.load(f)
        data = np.load(os.path.join(path, "params.npz"))
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
        with open(os.path.join(path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        params = jax.tree_util.tree_unflatten(treedef, leaves)
        inputs = [IOSpec(n, tuple(s), np.dtype(d)) for n, s, d in spec["inputs"]]
        outputs = [IOSpec(n, tuple(s), np.dtype(d)) for n, s, d in spec["outputs"]]
        if apply_fn is None:
            # portable path: the artifact's jax.export modules ARE the
            # program — reconstruct apply_fn from the largest bucket's
            # module so the artifact loads with no Python source (the TRT
            # plan-file property; recompiles route through the modules too)
            apply_fn = self._portable_apply_fn(path, spec)
        model = Model(model_name or spec["name"], apply_fn, params,
                      inputs, outputs, spec["max_batch_size"],
                      spec["batch_buckets"])
        weights_addr, device_params = self.allocator.allocate_tree(params)
        try:
            return self._load_executables(path, model, weights_addr,
                                          device_params)
        except BaseException:
            self.allocator.deallocate_node(weights_addr)  # no error-path leak
            raise

    @staticmethod
    def _portable_apply_fn(path: str, spec: dict):
        """apply_fn synthesized from the artifact's jax.export modules:
        dispatches on the batch dimension to the matching bucket's module
        (each module is shape-exact, like a TRT profile).

        LAZY: modules deserialize on first invocation — an artifact whose
        serialized .xla executables all validate never touches (or needs)
        the portable modules."""
        from jax import export as jexport

        modules: dict = {}

        def _module(b: int):
            if b not in modules:
                shlo = os.path.join(path, f"bucket_{b}.shlo")
                if not os.path.exists(shlo):
                    raise ValueError(
                        f"this artifact was loaded without apply_fn and "
                        f"needs its portable module to (re)compile bucket "
                        f"{b}, but {shlo} is missing (saved by an older "
                        f"save_engine, or export was unavailable) — pass "
                        f"apply_fn to recompile from source")
                with open(shlo, "rb") as f:
                    modules[b] = jexport.deserialize(f.read())
            return modules[b]

        def apply_fn(params, inputs):
            batch = next(iter(inputs.values())).shape[0]
            if batch not in spec["batch_buckets"]:
                raise ValueError(f"no portable module for bucket {batch} "
                                 f"(have {sorted(spec['batch_buckets'])})")
            return _module(batch).call(params, inputs)

        return apply_fn

    def _load_executables(self, path: str, model: Model, weights_addr,
                          device_params) -> CompiledModel:
        import jax  # noqa: F401  (deserialization path may touch jax)
        executables: Dict[int, Any] = {}
        for b in model.batch_buckets:
            blob_path = os.path.join(path, f"bucket_{b}.xla")
            if os.path.exists(blob_path):
                try:
                    from jax.experimental import serialize_executable as se
                    with open(blob_path, "rb") as f:
                        blob, in_tree, out_tree = pickle.load(f)
                    exe = se.deserialize_and_load(blob, in_tree, out_tree)
                    # smoke-validate: serialized executables are topology- and
                    # machine-specific (the TRT plan-file caveat, sharper on
                    # XLA); recompile when the artifact doesn't match here
                    dummy = {
                        s.name: np.zeros(s.batched_shape(b), s.np_dtype)
                        for s in model.inputs
                    }
                    exe(device_params, dummy)
                    executables[b] = exe
                    continue
                except Exception as e:
                    log.warning("serialized executable for bucket %d unusable "
                                "on this topology (%s); recompiling", b,
                                type(e).__name__)
            executables[b] = None
        if any(v is None for v in executables.values()):
            compiled = self.compile_model(
                model, [b for b, v in executables.items() if v is None],
                _placed=(weights_addr, device_params))
            for b, exe in compiled.executables.items():
                executables[b] = exe
        return CompiledModel(model, self.device, executables, device_params,
                             allocator=self.allocator,
                             weights_addr=weights_addr)
