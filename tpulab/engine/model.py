"""Model: a named program + weights + IO contract (reference model.h:17-47,
model.cc:39-117 — engine introspection, binding info, optimization profiles).

A ``Model`` owns:
- ``apply_fn(params, inputs) -> outputs`` — a pure JAX function (dict in/out)
- ``params`` — the weight pytree (the reference's captured weights; Model owns
  them, reference runtime.cc:134-143 weight-capture)
- input/output ``IOSpec``s — named bindings with per-sample shapes/dtypes
  (reference binding introspection model.cc:73-117)
- ``batch_buckets`` — the supported batch sizes.  XLA compiles static shapes,
  so dynamic batch is served by padding up to the nearest bucket — the
  TPU-native replacement for TensorRT optimization profiles (model.cc:39-71):
  each bucket is one compiled program, chosen at dispatch.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


def default_batch_buckets(max_batch_size: int) -> List[int]:
    """Powers of two up to max (plus max itself): 1,2,4,...,max."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    buckets = []
    b = 1
    while b < max_batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch_size)
    return buckets


@dataclasses.dataclass(frozen=True)
class IOSpec:
    """One named binding (reference binding info: name/dims/dtype/size)."""

    name: str
    shape: Tuple[int, ...]       # per-sample shape (no batch dim)
    dtype: Any = np.float32

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.dtype)

    def elements_per_sample(self) -> int:
        return int(math.prod(self.shape)) if self.shape else 1

    def bytes_per_sample(self) -> int:
        return self.elements_per_sample() * self.np_dtype.itemsize

    def batched_shape(self, batch_size: int) -> Tuple[int, ...]:
        return (batch_size, *self.shape)


class Model:
    """A servable model (reference Model wrapping ICudaEngine + weights)."""

    def __init__(self, name: str,
                 apply_fn: Callable[[Any, Dict[str, Any]], Dict[str, Any]],
                 params: Any,
                 inputs: Sequence[IOSpec],
                 outputs: Sequence[IOSpec],
                 max_batch_size: int = 8,
                 batch_buckets: Optional[Sequence[int]] = None):
        self.name = name
        self.apply_fn = apply_fn
        self.params = params
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.max_batch_size = max_batch_size
        self.batch_buckets = sorted(batch_buckets or default_batch_buckets(max_batch_size))
        if self.batch_buckets[-1] != max_batch_size:
            raise ValueError("largest bucket must equal max_batch_size")
        self._bindings = {s.name: s for s in [*self.inputs, *self.outputs]}

    # -- introspection (reference model.cc binding queries) -----------------
    def binding(self, name: str) -> IOSpec:
        return self._bindings[name]

    @property
    def binding_names(self) -> List[str]:
        return list(self._bindings)

    def is_input(self, name: str) -> bool:
        return any(s.name == name for s in self.inputs)

    def binding_size_in_bytes(self, name: str, batch_size: int) -> int:
        return self.binding(name).bytes_per_sample() * batch_size

    def element_count(self, name: str, batch_size: int) -> int:
        return self.binding(name).elements_per_sample() * batch_size

    def bindings_size_in_bytes(self, batch_size: Optional[int] = None) -> int:
        """Total bytes of all bindings at a batch size (pool sizing input,
        reference inference_manager.cc:110-117)."""
        b = batch_size or self.max_batch_size
        return sum(self.binding_size_in_bytes(n, b) for n in self._bindings)

    def weights_size_in_bytes(self) -> int:
        import jax
        return sum(np.dtype(leaf.dtype).itemsize * int(math.prod(leaf.shape))
                   for leaf in jax.tree_util.tree_leaves(self.params)
                   if hasattr(leaf, "shape"))

    def pick_bucket(self, batch_size: int) -> int:
        """Smallest bucket >= batch_size (the 'profile selection')."""
        if batch_size > self.max_batch_size:
            raise ValueError(
                f"batch {batch_size} exceeds max_batch_size {self.max_batch_size}")
        for b in self.batch_buckets:
            if b >= batch_size:
                return b
        raise AssertionError  # unreachable: last bucket == max

    def __repr__(self) -> str:  # pragma: no cover
        ins = ",".join(s.name for s in self.inputs)
        outs = ",".join(s.name for s in self.outputs)
        return (f"Model({self.name}, in=[{ins}], out=[{outs}], "
                f"buckets={self.batch_buckets})")
