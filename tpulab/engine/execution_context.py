"""ExecutionContext: a pooled execution slot
(reference execution_context.h:20-51 — IExecutionContext created *without*
device memory so activation scratch is externally owned).

On TPU, XLA owns activation scratch inside the compiled program, so the
context is a pure *concurrency token* bound to a CompiledModel: holding one is
the right to have a dispatch in flight (reference SURVEY §7 "keep the
token-pool semantics even if memory is runtime-managed").  ``infer`` dispatches
asynchronously and returns device outputs immediately; ``synchronize`` blocks.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from tpulab.engine.runtime import CompiledModel


class ExecutionContext:
    """Execution slot over one compiled model (reference ExecutionContext)."""

    def __init__(self, compiled: CompiledModel, slot_id: int = 0):
        self.compiled = compiled
        self.slot_id = slot_id
        self._last_outputs: Optional[Dict[str, Any]] = None

    @property
    def model(self):
        return self.compiled.model

    def infer(self, device_inputs: Dict[str, Any], bucket: int) -> Dict[str, Any]:
        """Async dispatch of the pre-compiled program for ``bucket``
        (the cudaGraphLaunch analog — no tracing, no building, one call)."""
        outputs = self.compiled(bucket, device_inputs)
        self._last_outputs = outputs
        return outputs

    def synchronize(self) -> None:
        """Block until the last dispatch completes (reference ctx Synchronize)."""
        from tpulab.tpu.sync import tpu_sync_standard
        if self._last_outputs is not None:
            tpu_sync_standard(self._last_outputs)
            self._last_outputs = None

    def binding_size_in_bytes(self, name: str, batch_size: int) -> int:
        return self.model.binding_size_in_bytes(name, batch_size)
