"""Buffers/Bindings: per-request host staging + device tensors
(reference buffers.h:51-155, bindings.h:59-121 — host+device memory stacks
with per-binding carve-out, async copies on the buffers' stream).

TPU shape of the same design:
- ``Buffers`` owns a pinned-host staging stack (BlockStack over the staging
  allocator).  Device memory is *not* pre-carved: XLA owns layouts/tiling, so
  device tensors materialize at transfer; the Buffers' pool slot is what
  bounds per-request memory (the reference's backpressure role).
- ``Bindings`` carves one padded numpy view per input binding off the staging
  stack (zero-copy for the user's fill), dispatches async H2D per binding
  (``copy_to_device``), holds the resulting device arrays, and lands outputs
  back into staging views on D2H.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from tpulab.core.dtypes import dtype_from_numpy  # noqa: F401 (re-export)
from tpulab.engine.model import Model
from tpulab.memory.arena import BlockArena, BlockStack
from tpulab.memory.block import FixedSizeBlockAllocator
from tpulab.tpu.allocators import make_staging_allocator
from tpulab.tpu.copy import copy_to_device, copy_to_host
from tpulab.tpu.sync import tpu_sync_standard


class _NativeStagingStack:
    """BlockStack-shaped adapter over the native transactional allocator
    (cpp/src/transactional.cc): per-binding carves are 20 ns native bump
    allocations instead of Python block-stack arithmetic."""

    def __init__(self, block_bytes: int):
        from tpulab import native
        self._alloc = native.NativeTransactionalAllocator(
            block_size=block_bytes)
        self._live: List[int] = []

    def allocate(self, nbytes: int, alignment: int = 64) -> int:
        addr = self._alloc.allocate_node(nbytes, alignment)
        self._live.append(addr)
        return addr

    def reset(self) -> None:
        for addr in self._live:
            self._alloc.deallocate_node(addr)
        self._live.clear()

    def close(self) -> None:
        self.reset()
        self._alloc.close()


class Buffers:
    """One pool slot of staging memory (reference FixedBuffers)."""

    def __init__(self, host_stack_bytes: int, device=None, block_size: int = 0,
                 transfer_engine=None, coalesce_h2d: bool = False):
        block = block_size or host_stack_bytes
        self._arena = None
        self._stack = None
        try:
            from tpulab import native
            if native.enabled():
                self._stack = _NativeStagingStack(block)
        except Exception:  # pragma: no cover - fall back on load issues
            self._stack = None
        if self._stack is None:
            self._arena = BlockArena(
                FixedSizeBlockAllocator(make_staging_allocator(), block),
                cached=True)
            self._stack = BlockStack(self._arena)
        self.device = device
        self.transfer_engine = transfer_engine
        self.coalesce_h2d = coalesce_h2d

    def create_bindings(self, model: Model, batch_size: int) -> "Bindings":
        """Carve per-binding staging views (reference CreateBindings)."""
        return Bindings(self, model, batch_size)

    def _carve(self, nbytes: int) -> np.ndarray:
        from tpulab.memory.descriptor import host_view
        addr = self._stack.allocate(nbytes, alignment=64)
        return np.frombuffer(host_view(addr, nbytes), dtype=np.uint8)

    def reset(self) -> None:
        """Return all carved memory (runs as the pool's on_return hook)."""
        self._stack.reset()

    def release(self) -> None:
        self._stack.reset()
        if self._arena is not None:
            self._arena.shrink_to_fit()


class Bindings:
    """Per-inference tensor state (reference Bindings).

    Lifecycle: fill host views -> ``copy_to_device()`` -> execute ->
    ``copy_from_device(outputs)`` -> ``synchronize()`` -> read host outputs.
    """

    def __init__(self, buffers: Buffers, model: Model, batch_size: int):
        self.model = model
        self.batch_size = batch_size
        self.bucket = model.pick_bucket(batch_size)
        self.device = buffers.device
        self._buffers = buffers
        self.host_inputs: Dict[str, np.ndarray] = {}
        self.host_outputs: Dict[str, np.ndarray] = {}
        self.device_inputs: Dict[str, Any] = {}
        self.device_outputs: Dict[str, Any] = {}
        #: set by the coalesced-fetch post stage: private host arrays that
        #: outputs() prefers over the staging views (saves a copy)
        self.fetched_outputs: Dict[str, np.ndarray] = {}
        for spec in model.inputs:
            raw = buffers._carve(spec.bytes_per_sample() * self.bucket)
            arr = raw.view(spec.np_dtype).reshape(spec.batched_shape(self.bucket))
            self.host_inputs[spec.name] = arr
        for spec in model.outputs:
            raw = buffers._carve(spec.bytes_per_sample() * self.bucket)
            arr = raw.view(spec.np_dtype).reshape(spec.batched_shape(self.bucket))
            self.host_outputs[spec.name] = arr

    # -- fill ---------------------------------------------------------------
    def set_input(self, name: str, array: np.ndarray) -> None:
        """Copy user data into the staging view (pads to the bucket)."""
        spec = self.model.binding(name)
        if not self.model.is_input(name):
            raise KeyError(f"{name} is not an input binding")
        view = self.host_inputs[name]
        if array.dtype != spec.np_dtype:
            raise TypeError(f"input {name} dtype {array.dtype} != binding "
                            f"dtype {spec.np_dtype} (no implicit casts on "
                            f"the serving path)")
        n = array.shape[0]
        if n != self.batch_size:
            raise ValueError(f"input {name} batch {n} != bindings batch "
                             f"{self.batch_size}")
        view[:n] = array
        if n < self.bucket:
            view[n:] = 0  # deterministic padding

    # -- transfers ----------------------------------------------------------
    def copy_to_device(self) -> None:
        """H2D of every input binding (reference CopyToDevice).  With the
        manager's coalesce_h2d flag the bindings ride the TransferEngine's
        batched put (one device_put per cycle across concurrent requests);
        otherwise each binding dispatches its own async put."""
        from tpulab import chaos
        # chaos: host->device transfer fault site (error = failed staging
        # put, surfaces through the dispatch stage's failure path; delay =
        # a congested link)
        chaos.trip("device.transfer")
        engine = self._buffers.transfer_engine
        if engine is not None and self._buffers.coalesce_h2d:
            # blocks this dispatch thread until the collector's next cycle;
            # the manager sizes the dispatch pool up under coalesce_h2d so
            # a full cycle's worth of requests can coalesce
            self.device_inputs = engine.put(
                dict(self.host_inputs), self.device).result()
            return
        for name, host in self.host_inputs.items():
            self.device_inputs[name] = copy_to_device(host, self.device)

    def copy_from_device(self, outputs: Dict[str, Any]) -> None:
        """Record device outputs; D2H lands in staging on synchronize()
        (reference CopyFromDevice async D2H)."""
        self.device_outputs = dict(outputs)

    def synchronize(self) -> Dict[str, np.ndarray]:
        """Block until results; materialize host output views
        (reference Bindings::Synchronize).

        Goes through the shared TransferEngine when available so concurrent
        requests share one D2H flush (see tpulab.tpu.transfer)."""
        engine = self._buffers.transfer_engine
        if engine is not None:
            host = engine.fetch_sync(self.device_outputs)
            for name, arr in host.items():
                out = self.host_outputs.get(name)
                if out is not None:
                    np.copyto(out, arr)
        else:
            tpu_sync_standard(self.device_outputs)
            for name, dev in self.device_outputs.items():
                out = self.host_outputs.get(name)
                if out is not None:
                    copy_to_host(dev, out)
        return {n: self.host_outputs[n][:self.batch_size]
                for n in self.host_outputs}

    def outputs(self) -> Dict[str, np.ndarray]:
        """Unpadded host outputs (valid after synchronize / fetch)."""
        if self.fetched_outputs:
            return {n: arr[:self.batch_size]
                    for n, arr in self.fetched_outputs.items()}
        return {n: self.host_outputs[n][:self.batch_size]
                for n in self.host_outputs}

    def release(self) -> None:
        self.host_inputs.clear()
        self.host_outputs.clear()
        self.device_inputs.clear()
        self.device_outputs.clear()
        self.fetched_outputs = {}
