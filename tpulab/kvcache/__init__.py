"""tpulab.kvcache — tiered KV cache: the host-memory offload tier.

HBM KV pressure used to destroy state (preempted requests re-prefilled,
evicted prefix-cache entries vanished); this package demotes that state
to a budgeted host-RAM tier and promotes it back — recompute-free
preemption and a spill-backed prefix cache (docs/PERFORMANCE.md "KV
tiering", docs/SERVING.md).

- :class:`HostKVStore` — budgeted LRU host tier on the
  :mod:`tpulab.memory` allocator/descriptor framework.
- :class:`KVOffloadManager` — async device<->host swap policy over a
  :class:`~tpulab.engine.paged.PagedKVPool`, riding the
  :class:`~tpulab.tpu.transfer.TransferEngine` (write-behind swap-out).

Wire-up: ``ContinuousBatcher(..., kv_offload=...)`` (True / budget bytes
/ a manager instance).
"""

from tpulab.kvcache.host_store import HostKVStore  # noqa: F401
from tpulab.kvcache.offload import (DEFAULT_HOST_BUDGET,  # noqa: F401
                                    KVOffloadManager, SwapHandle,
                                    benchmark_kv_offload)

__all__ = ["HostKVStore", "KVOffloadManager", "SwapHandle",
           "DEFAULT_HOST_BUDGET", "benchmark_kv_offload"]
