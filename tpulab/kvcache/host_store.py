"""Host-memory KV tier: budgeted, LRU, page-granular byte store.

The serving stack's KV pages live in HBM (:class:`~tpulab.engine.paged.
PagedKVPool`); this module is the tier BELOW it — host RAM holding KV
snapshots that HBM pressure pushed out (preempted lanes, evicted prefix
cache entries).  It is deliberately dumb: keys map to opaque byte
payloads with shape/dtype metadata, an LRU order, and a hard byte
budget.  All tiering *policy* (what to demote, when to promote) lives in
:class:`~tpulab.kvcache.offload.KVOffloadManager`.

The storage itself comes from the :mod:`tpulab.memory` framework — each
entry owns a :class:`~tpulab.memory.descriptor.Descriptor` from a host
``IAllocator`` (default: the mmap-backed
:class:`~tpulab.memory.raw_allocators.MallocAllocator` behind the
``make_allocator`` facade), written through the descriptor's zero-copy
numpy view.  That finally puts the typed allocator/descriptor library —
the reference framework's core (SURVEY §2.1) — on the serving hot path
instead of beside it.

Thread safety: one lock.  The TransferEngine collector thread writes
(swap-out completions land here), the scheduler thread reads/promotes.
``get`` returns a *copy*, never the live view: an LRU eviction from
another thread closes the backing mapping, and a zero-copy view must not
outlive it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple

import numpy as np

from tpulab.memory.allocator import make_allocator
from tpulab.memory.descriptor import Descriptor
from tpulab.memory.raw_allocators import MallocAllocator


class _Entry:
    __slots__ = ("desc", "shape", "dtype", "nbytes")

    def __init__(self, desc: Descriptor, shape: Tuple[int, ...], dtype,
                 nbytes: int):
        self.desc = desc
        self.shape = shape
        self.dtype = dtype
        self.nbytes = nbytes


class HostKVStore:
    """Budgeted LRU byte store for KV page payloads (module docstring).

    ``budget_bytes`` caps resident payload bytes; inserting past it
    evicts cold entries first, and a single payload larger than the whole
    budget is refused (``put`` returns False — the caller's drop path,
    identical to not having a host tier for that entry).
    """

    def __init__(self, budget_bytes: int, allocator=None):
        if budget_bytes <= 0:
            raise ValueError("budget_bytes must be > 0")
        self.budget_bytes = int(budget_bytes)
        self._alloc = allocator or make_allocator(MallocAllocator())
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        # -- counters (poll-advanced by KVTierMetrics) ----------------------
        self.puts = 0          # payloads stored
        self.hits = 0          # get/pop found the key
        self.misses = 0        # get/pop did not
        self.evictions = 0     # LRU entries pushed out by budget pressure
        self.drops = 0         # payloads refused (larger than the budget)
        self.peeks = 0         # non-LRU export reads (fabric fetches)

    # -- sizing --------------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def headroom_bytes(self) -> int:
        """Bytes storable right now WITHOUT evicting (admission's host-tier
        headroom signal reads this)."""
        with self._lock:
            return max(0, self.budget_bytes - self._bytes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    # -- the tier ------------------------------------------------------------
    def put(self, key, array: np.ndarray) -> bool:
        """Store ``array`` under ``key`` (replacing any incumbent), evicting
        LRU entries until it fits.  False = refused (payload exceeds the
        whole budget) — the entry is simply NOT in the tier, which callers
        must treat as today's drop-and-recompute path."""
        array = np.ascontiguousarray(array)
        nbytes = int(array.nbytes)
        with self._lock:
            if nbytes > self.budget_bytes:
                self.drops += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                old.desc.release()
            while self._bytes + nbytes > self.budget_bytes and self._entries:
                _, cold = self._entries.popitem(last=False)
                self._bytes -= cold.nbytes
                cold.desc.release()
                self.evictions += 1
            desc = self._alloc.allocate_descriptor(max(1, nbytes))
            desc.numpy(array.dtype, array.shape)[...] = array
            self._entries[key] = _Entry(desc, array.shape, array.dtype,
                                        nbytes)
            self._bytes += nbytes
            self.puts += 1
            return True

    def get(self, key) -> Optional[np.ndarray]:
        """A COPY of the payload (and an LRU touch), or None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e.desc.numpy(e.dtype, e.shape).copy()

    def peek(self, key) -> Optional[np.ndarray]:
        """A COPY of the payload WITHOUT the LRU touch — the fleet KV
        fabric's export read (tpulab.kvfabric).  A remote replica pulling
        a prefix must not look like local reuse: under a fetch storm,
        ``get``'s recency bump would pin fabric-popular entries hot and
        evict the owner's OWN working set instead.  Counted separately
        (``peeks``) so fetch traffic never skews hit/miss ratios."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self.peeks += 1
            return e.desc.numpy(e.dtype, e.shape).copy()

    def pop(self, key) -> Optional[np.ndarray]:
        """``get`` + remove — the one-shot read for preemption snapshots
        (a restored lane's host copy is dead weight)."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                self.misses += 1
                return None
            self._bytes -= e.nbytes
            self.hits += 1
            out = e.desc.numpy(e.dtype, e.shape).copy()
            e.desc.release()
            return out

    def remove(self, key) -> bool:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return False
            self._bytes -= e.nbytes
            e.desc.release()
            return True

    def clear(self) -> None:
        with self._lock:
            for e in self._entries.values():
                e.desc.release()
            self._entries.clear()
            self._bytes = 0
