"""KV offload manager: device<->host tiering policy over the paged pool.

HBM pressure in the serving stack used to destroy state: a preempted
request re-prefilled prompt+generated from scratch, an evicted prefix
cache entry was simply gone.  This module turns both into *demotions* to
a host-RAM tier (:class:`~tpulab.kvcache.host_store.HostKVStore`) and
back:

- **Preemption** — :meth:`KVOffloadManager.swap_out` snapshots the
  victim lane's live KV pages device->host *asynchronously* (device-side
  gather dispatched inline, the host fetch rides the
  :class:`~tpulab.tpu.transfer.TransferEngine` collector thread — the
  decode tick never blocks on swap-out: write-behind).  On resume,
  :meth:`restore` scatters the snapshot into freshly allocated pages and
  the request continues decoding with ZERO prefill dispatches.
- **Prefix-cache eviction** — :meth:`demote` moves an evicted entry's
  page to the host tier keyed by its prompt digest; :meth:`promote`
  brings it back on the next lookup hit, making the prefix cache's
  effective capacity host-RAM-sized.

Every degraded path is the pre-offload behavior: a snapshot that was
dropped (budget), failed (transfer error) or chaos-tripped
(``kvcache.swap``) simply leaves the request on today's
re-prefill/recompute path — offload can only *save* work, never corrupt
a lane.

Sharded pools (mesh serving): the snapshot gather of a sharded pool
produces a payload sharded like the pool (KV-heads dim); the
TransferEngine fetch assembles it into ONE unsharded host array, so the
host tier and the disagg wire always hold mesh-portable bytes, and
restore's device_put re-shards them onto the LOCAL pool placement — a
decode replica on a different mesh imports bit-exactly.

Ordering safety: the gather that snapshots pages is dispatched BEFORE
the pages are released, and XLA executes a device's programs in
dispatch order — any later write into a recycled page is ordered after
the gather's read, so the snapshot observes the victim's bytes even
though the fetch completes later.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from typing import Any, Dict, List, Optional

import numpy as np

from tpulab import chaos
from tpulab.kvcache.host_store import HostKVStore

log = logging.getLogger("tpulab.kvcache")

#: default host-tier budget (bytes) when ``kv_offload=True``-style knobs
#: construct the manager implicitly
DEFAULT_HOST_BUDGET = 256 << 20

#: swap-handle states
_PENDING, _RESIDENT, _DROPPED, _FAILED = range(4)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


class SwapHandle:
    """One lane snapshot's lifecycle token.  Returned by ``swap_out``;
    consumed by ``restore``.  ``wait()`` is the write-behind fence —
    True once the snapshot is resident in the host tier."""

    __slots__ = ("key", "n_pages", "length", "_done", "_state")

    def __init__(self, key, n_pages: int, length: int):
        self.key = key
        self.n_pages = n_pages
        self.length = length            # resident positions the snapshot covers
        self._done = threading.Event()
        self._state = _PENDING

    def wait(self, timeout: Optional[float] = None) -> bool:
        """True when the snapshot landed in the host tier; False while
        still in flight (timeout) or when it was dropped/failed."""
        self._done.wait(timeout)
        return self._state == _RESIDENT

    @property
    def resident(self) -> bool:
        return self._state == _RESIDENT


class KVOffloadManager:
    """Device<->host KV tiering for one :class:`PagedKVPool` (module
    docstring).  ``transfer`` is an optional shared
    :class:`~tpulab.tpu.transfer.TransferEngine` (one is owned
    otherwise); ``metrics`` an optional
    :class:`~tpulab.utils.metrics.KVTierMetrics` observing swap
    latency/bytes at the source.
    """

    #: bound on how long a resume waits for its write-behind snapshot to
    #: land before falling back to re-prefill (the snapshot is normally
    #: resident long before the victim reaches the queue head)
    RESTORE_WAIT_S = 10.0

    def __init__(self, pool, host_budget_bytes: int = DEFAULT_HOST_BUDGET,
                 store: Optional[HostKVStore] = None, transfer=None,
                 metrics=None):
        import jax
        import jax.numpy as jnp

        self.pool = pool
        # identity check, not truthiness: an EMPTY HostKVStore is falsy
        # (__len__ == 0) and `store or ...` would silently replace it
        self.store = store if store is not None \
            else HostKVStore(host_budget_bytes)
        if transfer is None:
            from tpulab.tpu.transfer import TransferEngine
            transfer = TransferEngine(name="kvswap")
            self._owns_transfer = True
        else:
            self._owns_transfer = False
        self._transfer = transfer
        self.metrics = metrics
        # per-page payload size: pool store is (L, P, 2, S, Hkv, D); one
        # page carries every layer's K+V rows for its S slots
        shape = tuple(pool.kv.shape)
        self.page_nbytes = int(np.prod(shape) // shape[1]
                               * jnp.dtype(pool.dtype).itemsize)
        # page-index gathers/scatters, padded to pow2 page counts so the
        # jit cache stays at log2 variants (padding rides the RESERVED
        # scratch page 0: reads of it are discarded, writes to it are
        # harmless by the pool's own contract).  Cached per (pow2 count,
        # POOL PLACEMENT): the placement — mesh axes + spec + device set,
        # or the single bound device — must be part of the key, so a pool
        # re-pointed at a different mesh (a decode replica importing onto
        # its own topology) can never reuse a scatter compiled for the
        # old placement.  Sharded pools round-trip bit-exactly: the
        # gather's payload is fetched to ONE unsharded host array (the
        # host tier and the disagg wire always hold mesh-portable bytes)
        # and restore re-shards it onto the local placement at device_put.
        self._gather_fns: Dict[Any, Any] = {}
        self._scatter_fns: Dict[Any, Any] = {}
        self._lock = threading.Lock()
        self._ops_cv = threading.Condition(self._lock)
        self._seq = 0
        self._pending_ops = 0   # write-behind copies still in flight
        # -- counters (KVTierMetrics.poll advances from these) --------------
        self.swap_outs = 0              # lane snapshots dispatched
        self.swap_ins = 0               # lane snapshots restored
        self.swap_out_bytes = 0
        self.swap_in_bytes = 0
        self.swap_failures = 0          # chaos/transfer degradations
        self.swap_drops = 0             # host-budget-refused snapshots
        self.demotions = 0              # prefix pages demoted to host
        self.promotions = 0             # prefix pages promoted back
        self.recompute_tokens_saved = 0  # prefill tokens resumes skipped

    # -- placement-keyed jits ---------------------------------------------
    def _placement_key(self):
        """Fingerprint of where pool-shaped arrays live: mesh axes + spec
        + device ids for a sharded pool, the bound device otherwise."""
        sh = getattr(self.pool, "kv_sharding", None)
        if sh is None:
            d = self.pool.device
            return ("dev", getattr(d, "id", id(d)))
        return ("mesh", tuple(sh.mesh.shape.items()), str(sh.spec),
                tuple(int(d.id) for d in sh.mesh.devices.flat))

    def _gather_fn(self, n_padded: int):
        import jax
        key = (n_padded, self._placement_key())
        fn = self._gather_fns.get(key)
        if fn is None:
            fn = jax.jit(lambda kv, idx: kv[:, idx])
            self._gather_fns[key] = fn
        return fn

    def _scatter_fn(self, n_padded: int):
        import jax
        key = (n_padded, self._placement_key())
        fn = self._scatter_fns.get(key)
        if fn is None:
            fn = jax.jit(lambda kv, idx, data: kv.at[:, idx].set(data),
                         donate_argnums=(0,))
            self._scatter_fns[key] = fn
        return fn

    def _payload_placement(self):
        """device_put target for restore/promote payloads — the pool's
        NamedSharding under a mesh (the import RE-SHARDS host bytes onto
        the local topology), the pool device otherwise."""
        return getattr(self.pool, "placement", self.pool.device)

    # -- lane swap (preemption) ----------------------------------------------
    def swap_out(self, pages: List[int], length: int, kv,
                 key=None) -> Optional[SwapHandle]:
        """Snapshot ``pages`` (covering positions ``[0, length)``) to the
        host tier.  Dispatches the device gather and returns immediately;
        the D2H fetch + store happen behind the decode loop (write-
        behind).  None = degraded (chaos/failure): caller keeps today's
        drop-and-re-prefill path.

        ``key`` overrides the minted ``("lane", seq)`` store key — the
        disaggregation path keys finished-prefill exports by prompt
        digest (``("ship", digest)``) so the shipper can find them."""
        if not pages or length <= 0:
            return None
        try:
            if chaos.trip("kvcache.swap") == "drop":
                raise chaos.ChaosError("injected swap drop")
            n = len(pages)
            idx = np.zeros((_next_pow2(n),), np.int32)  # pad -> scratch 0
            idx[:n] = pages
            gathered = self._gather_fn(idx.shape[0])(kv, idx)
        except Exception as e:  # noqa: BLE001 - degrade, never corrupt
            self.swap_failures += 1
            log.warning("KV swap-out degraded to recompute path: %s: %s",
                        type(e).__name__, str(e)[:200])
            return None
        with self._lock:
            self._seq += 1
            handle = SwapHandle(key if key is not None
                                else ("lane", self._seq), n, length)
            self._pending_ops += 1
        t0 = _time.perf_counter()
        fut = self._transfer.fetch(gathered)
        fut.add_done_callback(
            lambda f: self._on_fetched(handle, f, n, t0, ("lane",)))
        return handle

    def _on_fetched(self, handle: SwapHandle, fut, n: int, t0: float,
                    kind) -> None:
        """TransferEngine-thread completion: land the snapshot in the host
        tier (the future itself is dropped afterwards, so the only host
        copy is the budgeted one)."""
        try:
            arr = np.asarray(fut.result())[:, :n]  # strip pow2 padding
            stored = self.store.put(handle.key, arr)
        except Exception:  # noqa: BLE001 - collector thread must live
            handle._state = _FAILED
            self.swap_failures += 1
            log.exception("KV swap-out fetch failed")
        else:
            if stored:
                handle._state = _RESIDENT
                self.swap_outs += 1
                self.swap_out_bytes += arr.nbytes
                if self.metrics is not None:
                    self.metrics.observe_swap_out(
                        _time.perf_counter() - t0, arr.nbytes)
            else:
                # budget-rejected put: NOT a transfer failure — a distinct
                # counter (and log line) so an undersized host budget is
                # diagnosable separately from a flaky transfer path
                handle._state = _DROPPED
                self.swap_drops += 1
                log.warning(
                    "KV swap-out dropped: host tier refused %d bytes "
                    "(budget %d, headroom %d) — host budget undersized?",
                    arr.nbytes, self.store.budget_bytes,
                    self.store.headroom_bytes)
        finally:
            handle._done.set()
            with self._ops_cv:
                self._pending_ops -= 1
                self._ops_cv.notify_all()

    def restore(self, handle: SwapHandle, pages: List[int], kv):
        """Scatter ``handle``'s snapshot into ``pages`` (freshly allocated
        by the caller, same count).  Returns the new donated pool buffer,
        or None when the snapshot is unavailable (still in flight past
        :data:`RESTORE_WAIT_S`, dropped, failed, or chaos-tripped) — the
        caller then re-prefills exactly as before offload existed.

        Degradation boundary: every failure BEFORE the scatter dispatch
        returns None with ``kv`` untouched.  A failure in the scatter
        itself propagates — the donated buffer is gone and the scheduler's
        pool-reset recovery path must run, same as any failed step."""
        import jax

        t0 = _time.perf_counter()
        try:
            if chaos.trip("kvcache.swap") == "drop":
                raise chaos.ChaosError("injected swap drop")
            if not handle.wait(self.RESTORE_WAIT_S):
                raise chaos.ChaosError("snapshot unavailable")
            arr = self.store.pop(handle.key)
            if arr is None or len(pages) != handle.n_pages:
                raise chaos.ChaosError("snapshot evicted from host tier")
            n = handle.n_pages
            idx = np.zeros((_next_pow2(n),), np.int32)  # pad -> scratch 0
            idx[:n] = pages
            if n != idx.shape[0]:
                # padded slots all land on the reserved scratch page 0,
                # so their payload is never read back: pad with ONE zero
                # page broadcast across the pad width instead of
                # np.repeat-ing the last real page (which allocated and
                # shipped real-page copies for every non-pow2 snapshot)
                zero = np.zeros_like(arr[:, :1])
                pad = np.broadcast_to(
                    zero, (arr.shape[0], idx.shape[0] - n) + arr.shape[2:])
                arr = np.concatenate([arr, pad], axis=1)
            data = jax.device_put(arr, self._payload_placement())
        except Exception as e:  # noqa: BLE001 - pre-dispatch: degrade
            self.swap_failures += 1
            self.store.remove(handle.key)
            log.warning("KV swap-in degraded to re-prefill: %s: %s",
                        type(e).__name__, str(e)[:200])
            return None
        new_kv = self._scatter_fn(idx.shape[0])(kv, idx, data)
        self.swap_ins += 1
        self.swap_in_bytes += handle.n_pages * self.page_nbytes
        self.recompute_tokens_saved += handle.length
        if self.metrics is not None:
            self.metrics.observe_swap_in(
                _time.perf_counter() - t0,
                handle.n_pages * self.page_nbytes)
        return new_kv

    def discard(self, handle: SwapHandle) -> None:
        """Forget a snapshot that will never be restored (request
        cancelled/expired while queued)."""
        self.store.remove(handle.key)

    # -- KV shipping (tpulab.disagg) -----------------------------------------
    def take_snapshot(self, handle: SwapHandle,
                      timeout: Optional[float] = None) -> Optional[np.ndarray]:
        """One-shot fetch of a snapshot's host payload for wire export
        (the disaggregation path).  Waits out the write-behind fence,
        then POPS the entry — after a successful export the only copy is
        the wire payload.  None when the snapshot was dropped/failed or
        evicted (the caller degrades to shipping nothing: the decode
        side prefills locally)."""
        if not handle.wait(self.RESTORE_WAIT_S if timeout is None
                           else timeout):
            return None
        return self.store.pop(handle.key)

    def adopt(self, key, array: np.ndarray,
              length: int) -> Optional[SwapHandle]:
        """Land an externally produced snapshot (a shipped-KV import) in
        the host tier and mint the already-RESIDENT handle that
        :meth:`restore` consumes — the decode replica's admit-from-
        shipped-KV entry point.  None when the budget refuses the
        payload (counted in ``swap_drops``; the caller degrades to local
        prefill)."""
        array = np.ascontiguousarray(array)
        n = int(array.shape[1])
        if not self.store.put(key, array):
            self.swap_drops += 1
            log.warning("shipped KV snapshot refused by host tier "
                        "(%d bytes, budget %d)", array.nbytes,
                        self.store.budget_bytes)
            return None
        handle = SwapHandle(key, n, int(length))
        handle._state = _RESIDENT
        handle._done.set()
        return handle

    # -- prefix-cache tiering ------------------------------------------------
    def demote(self, digest: bytes, page: int, kv) -> None:
        """Async-copy one evicted prefix page to the host tier (called by
        the cache's eviction path BEFORE the page is released — dispatch
        order makes the snapshot safe, see module docstring)."""
        try:
            if chaos.trip("kvcache.swap") == "drop":
                raise chaos.ChaosError("injected swap drop")
            gathered = self._gather_fn(1)(kv, np.asarray([page], np.int32))
        except Exception as e:  # noqa: BLE001 - the entry just drops
            self.swap_failures += 1
            log.warning("prefix demotion skipped: %s: %s",
                        type(e).__name__, str(e)[:200])
            return
        t0 = _time.perf_counter()
        with self._lock:
            self._pending_ops += 1
        fut = self._transfer.fetch(gathered)

        def land(f):
            try:
                if self.store.put(("px", digest), np.asarray(f.result())):
                    self.demotions += 1
                    self.swap_out_bytes += self.page_nbytes
                    if self.metrics is not None:
                        self.metrics.observe_swap_out(
                            _time.perf_counter() - t0, self.page_nbytes)
            except Exception:  # noqa: BLE001
                self.swap_failures += 1
                log.exception("prefix demotion fetch failed")
            finally:
                with self._ops_cv:
                    self._pending_ops -= 1
                    self._ops_cv.notify_all()

        fut.add_done_callback(land)

    def has_prefix(self, digest: bytes) -> bool:
        return ("px", digest) in self.store

    def promote(self, digest: bytes, page: int, kv):
        """Upload a demoted prefix page into ``page``.  Returns the new
        donated pool buffer, or None (miss/failure — caller releases the
        page and recomputes, today's path)."""
        import jax

        t0 = _time.perf_counter()
        try:
            if chaos.trip("kvcache.swap") == "drop":
                raise chaos.ChaosError("injected swap drop")
            arr = self.store.pop(("px", digest))
            if arr is None:
                return None
            data = jax.device_put(arr, self._payload_placement())
        except Exception as e:  # noqa: BLE001 - pre-dispatch: degrade
            self.swap_failures += 1
            log.warning("prefix promotion degraded to recompute: %s: %s",
                        type(e).__name__, str(e)[:200])
            return None
        new_kv = self._scatter_fn(1)(kv, np.asarray([page], np.int32), data)
        self.promotions += 1
        self.swap_in_bytes += self.page_nbytes
        if self.metrics is not None:
            self.metrics.observe_swap_in(_time.perf_counter() - t0,
                                         self.page_nbytes)
        return new_kv

    # -- load signals ---------------------------------------------------------
    def headroom_pages(self) -> int:
        """How many more KV pages the host tier can absorb without
        evicting (admission's host-tier headroom term)."""
        return self.store.headroom_bytes // max(1, self.page_nbytes)

    def demotable_pages(self, prefix_cache) -> int:
        """Device pages that pressure could DEMOTE instead of drop right
        now: capped both by what the cache holds and by host headroom."""
        cached = len(prefix_cache) if prefix_cache is not None else 0
        return min(cached, self.headroom_pages())

    # -- lifecycle ------------------------------------------------------------
    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every write-behind copy (lane swap-outs AND prefix
        demotions) has settled (tests, shutdown).  False on timeout."""
        with self._ops_cv:
            return self._ops_cv.wait_for(
                lambda: self._pending_ops == 0, timeout)

    def close(self) -> None:
        self.drain(timeout=2.0)
        if self._owns_transfer:
            self._transfer.shutdown()
        self.store.clear()


def benchmark_kv_offload(lanes: int = 2, steps: int = 20,
                         prompt_len: int = 12, page_size: int = 8,
                         d_model: int = 64, n_heads: int = 4,
                         n_layers: int = 2, vocab: int = 256,
                         n_low: int = 4, n_hi: int = 4,
                         dtype=None) -> Dict[str, Any]:
    """The bench ``kv_offload`` row: goodput and re-prefill dispatches
    under ~2x KV oversubscription, host tier on vs off.

    The workload keeps ``n_low + n_hi`` requests outstanding against a
    pool sized for ``lanes`` residents (outstanding KV demand ~2x the
    pool): the low-priority half decodes long sequences, and each
    high-priority preemptor is injected the moment a low lane is
    observed decoding — every preemption then either re-prefills (tier
    off) or swaps (tier on).  ``re_prefill_dispatches`` counts prefill
    passes beyond the one each request legitimately pays; with the tier
    on it should collapse toward zero.  On CPU jit the dispatch counts
    are the signal (a re-prefill forward is cheap there); on-device each
    avoided re-prefill is a whole prompt+generated forward not burned
    twice, so goodput is the headline.
    """
    import threading as _th
    import time

    import jax.numpy as jnp

    from tpulab.engine.paged import ContinuousBatcher
    from tpulab.models.transformer import init_transformer_params

    dtype = dtype or jnp.float32
    low_steps = 2 * steps               # long victims: a real resume window
    max_len = prompt_len + low_steps + 4
    pages_per_req = (max_len + page_size - 1) // page_size
    n_pages = lanes * pages_per_req + 1
    outstanding = n_low + n_hi
    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)
    rng = np.random.default_rng(0)
    low_prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
                   for _ in range(n_low)]
    hi_prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
                  for _ in range(n_hi)]

    def mode(offload_on: bool) -> Dict[str, Any]:
        cb = ContinuousBatcher(
            params, n_heads=n_heads, n_layers=n_layers, lanes=lanes,
            max_len=max_len, page_size=page_size, n_pages=n_pages,
            compute_dtype=dtype,
            kv_offload=DEFAULT_HOST_BUDGET if offload_on else None)
        try:
            # warm the prefill/decode compiles out of the measurement
            for f in [cb.submit(p, low_steps) for p in low_prompts[:lanes]]:
                f.result(timeout=300)
            for f in [cb.submit(p, steps) for p in hi_prompts[:lanes]]:
                f.result(timeout=300)
            pf0 = cb.prefill_dispatches
            decoding = _th.Semaphore(0)  # one permit per low decode token
            t0 = time.perf_counter()
            futs = [cb.submit(p, low_steps,
                              on_token=lambda _t, _i: decoding.release())
                    for p in low_prompts]
            for p in hi_prompts:
                # inject each preemptor only once a low lane is decoding,
                # so preemption (not plain admission) is what it exercises
                decoding.acquire(timeout=30)
                futs.append(cb.submit(p, steps, priority=10))
            for f in futs:
                f.result(timeout=300)
            wall = max(1e-6, time.perf_counter() - t0)
            entry = {
                "goodput_rps": round(len(futs) / wall, 2),
                "wall_s": round(wall, 3),
                "preemptions": cb.preemptions,
                "re_prefill_dispatches":
                    cb.prefill_dispatches - pf0 - len(futs),
            }
            mgr = cb.kv_offload
            if mgr is not None:
                entry.update(
                    swap_outs=mgr.swap_outs, swap_ins=mgr.swap_ins,
                    swap_out_mb=round(mgr.swap_out_bytes / 2**20, 2),
                    recompute_tokens_saved=mgr.recompute_tokens_saved,
                    swap_failures=mgr.swap_failures)
            return entry
        finally:
            cb.shutdown()

    return {
        "lanes": lanes, "steps": steps, "n_requests": n_low + n_hi,
        "pool_pages": n_pages,
        "oversubscription": round(
            outstanding * pages_per_req / n_pages, 2),
        "tier_off": mode(False),
        "tier_on": mode(True),
    }
