"""The bench ``obs_overhead`` row: what does arming the observability
plane cost the serving hot path?

The standard paged workload (the llm_latency row's shape) runs twice on
identical prompts/seeds: once bare, once with the flight recorder armed
on the batcher AND a debugz poller pulling live snapshots throughout
(the deployed shape: an operator dashboard polling Debug while traffic
flows).  Claims tracked:

- tokens are BIT-IDENTICAL armed vs off (the recorder observes, never
  steers — the house parity discipline);
- tok/s overhead < 5% (the acceptance bar; record assembly is a few
  dict writes per request);
- per-request record-assembly p99 is reported in ms (the direct cost,
  separated from scheduler noise).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict

__all__ = ["benchmark_obs_overhead"]


def benchmark_obs_overhead(n_requests: int = 16, steps: int = 32,
                           lanes: int = 4, prompt_len: int = 8,
                           vocab: int = 256, d_model: int = 64,
                           n_heads: int = 4, n_layers: int = 2,
                           d_ff: int = 256,
                           debug_poll_s: float = 0.02) -> Dict[str, Any]:
    import jax.numpy as jnp
    import numpy as np

    from tpulab.engine.paged import ContinuousBatcher
    from tpulab.models.transformer import init_transformer_params
    from tpulab.obs.debugz import debug_snapshot
    from tpulab.obs.flight import FlightRecorder

    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=d_ff)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(n_requests)]

    def run(armed: bool) -> Dict[str, Any]:
        fr = FlightRecorder() if armed else None
        cb = ContinuousBatcher(params, n_heads=n_heads, n_layers=n_layers,
                               lanes=lanes, max_len=prompt_len + steps + 8,
                               page_size=8, compute_dtype=jnp.float32,
                               flight=fr)
        stop = threading.Event()
        polls = [0]

        def poller():  # the operator-dashboard shape: Debug while serving
            while not stop.is_set():
                debug_snapshot(generation_engines={"llm": cb}, flight=fr)
                polls[0] += 1
                stop.wait(debug_poll_s)

        try:
            # warm the prefill/decode compiles OUT of the measured window
            cb.submit(prompts[0], steps).result(timeout=300)
            th = None
            if armed:
                th = threading.Thread(target=poller, daemon=True)
                th.start()
            t0 = time.perf_counter()
            futs = [cb.submit(p, steps) for p in prompts]
            toks = [f.result(timeout=300) for f in futs]
            wall = time.perf_counter() - t0
            if th is not None:
                stop.set()
                th.join(timeout=5)
            out = {"tok_s": round(n_requests * steps / wall, 2),
                   "wall_s": round(wall, 4), "tokens": toks,
                   "debug_polls": polls[0]}
            if fr is not None:
                aq = fr.assembly_quantiles()
                out["records_retained"] = len(fr)
                out["records_observed"] = fr.observed_total
                out["assembly_ms_p50"] = round(aq["p50"] * 1e3, 4)
                out["assembly_ms_p99"] = round(aq["p99"] * 1e3, 4)
            return out
        finally:
            stop.set()
            cb.shutdown()

    off = run(False)
    on = run(True)
    parity = off["tokens"] == on["tokens"]
    overhead = (off["tok_s"] - on["tok_s"]) / max(1e-9, off["tok_s"])
    row = {"n_requests": n_requests, "steps": steps, "lanes": lanes,
           "tok_s_off": off["tok_s"], "tok_s_on": on["tok_s"],
           "overhead_pct": round(100.0 * overhead, 2),
           "parity": bool(parity),
           "debug_polls": on["debug_polls"],
           "records_observed": on.get("records_observed", 0),
           "records_retained": on.get("records_retained", 0),
           "assembly_ms_p50": on.get("assembly_ms_p50", 0.0),
           "assembly_ms_p99": on.get("assembly_ms_p99", 0.0)}
    if not parity:
        row["parity_note"] = "TOKEN MISMATCH armed vs off — investigate"
    return row
