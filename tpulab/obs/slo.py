"""Per-tenant SLO burn rates over the flight-event stream.

An SLO is a budgeted promise: "99.9% of tenant X's online requests
succeed" leaves 0.1% of them as the **error budget**.  The *burn rate*
is how fast that budget is being spent — the window's observed bad
fraction divided by the budget — so burn 1.0 exactly exhausts the
budget over the objective period, burn 10 exhausts it 10x early, and
burn 0 means a clean window.  Following the multi-window discipline,
every objective is evaluated over a **fast** window (~5 min — pages on
sharp regressions within minutes) and a **slow** window (~1 h — holds
the page up through a sustained problem and suppresses one-blip noise).

Two objectives per (tenant, request class), both computed from the same
per-request wide events the flight recorder assembles (tap the recorder:
``flight.add_tap(tracker.observe)``):

- **availability**: a request whose terminal outcome is not SUCCESS
  counts against the budget (client-side CANCELLED is excluded from
  both sides — a tenant hanging up is not a serving failure).
- **latency**: a request whose ``e2e_s`` exceeds ``latency_objective_s``
  counts against the latency budget (``1 - latency_target``).

The **batch** request class is tracked (its burn gauges export) but
excluded from :meth:`scale_signal` — the fast-window burn the
:class:`~tpulab.fleet.autoscaler.FleetAutoscaler` may consume as a
secondary scale-up trigger — exactly like the queue-wait EWMA, which
batch-class admissions never feed: deliberately deferrable work must
not buy machines.

See docs/OBSERVABILITY.md "Fleet observability" for the exported
``_slo_*`` gauge families and worked burn-rate definitions.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

__all__ = ["SLOTracker"]

#: outcomes excluded from the availability objective entirely — the
#: client abandoned the request; the server did not fail it
_NEUTRAL_OUTCOMES = ("CANCELLED",)


class SLOTracker:
    """Multi-window burn-rate accounting per (tenant, request class).

    ``clock`` is injectable so tests can move time without sleeping;
    ``metrics`` is an optional
    :class:`~tpulab.utils.metrics.SLOMetrics` (per-event counters are
    updated on :meth:`observe`; the burn-rate gauges on
    :meth:`export` — call it from the scrape/fleetz path, not per
    request).  ``max_tenants`` bounds label cardinality the way any
    per-tenant exporter must: events beyond the cap are counted
    (``tenants_dropped``), not tracked."""

    def __init__(self, availability_objective: float = 0.999,
                 latency_objective_s: float = 2.0,
                 latency_target: float = 0.95,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 max_tenants: int = 256,
                 events_per_key: int = 8192,
                 clock: Callable[[], float] = time.time,
                 metrics=None):
        if not 0.0 < availability_objective < 1.0:
            raise ValueError("availability_objective must be in (0, 1)")
        if not 0.0 < latency_target < 1.0:
            raise ValueError("latency_target must be in (0, 1)")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        self.availability_objective = float(availability_objective)
        self.latency_objective_s = float(latency_objective_s)
        self.latency_target = float(latency_target)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.max_tenants = int(max_tenants)
        self.events_per_key = int(events_per_key)
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        # (tenant, request_class) -> deque of (t, error, breach)
        self._events: Dict[tuple, deque] = {}
        #: observability of the tracker itself
        self.observed_total = 0
        self.tenants_dropped = 0

    # -- ingestion (the flight-recorder tap) ---------------------------------
    def observe(self, event: Dict[str, Any]) -> None:
        """Account one completed request's wide event (flight-recorder
        schema: ``tenant``, ``request_class`` (absent = online),
        ``outcome``, ``e2e_s``).  Cheap and exception-free — it rides
        the request completion path."""
        outcome = str(event.get("outcome", "SUCCESS") or "SUCCESS")
        if outcome in _NEUTRAL_OUTCOMES:
            return
        tenant = str(event.get("tenant") or "anonymous")
        req_class = str(event.get("request_class") or "online")
        error = outcome not in ("SUCCESS", "")
        e2e = event.get("e2e_s")
        breach = (e2e is not None
                  and float(e2e) > self.latency_objective_s)
        now = float(self._clock())
        key = (tenant, req_class)
        with self._lock:
            ring = self._events.get(key)
            if ring is None:
                if len(self._events) >= self.max_tenants:
                    self.tenants_dropped += 1
                    return
                ring = deque(maxlen=self.events_per_key)
                self._events[key] = ring
            ring.append((now, error, breach))
            self.observed_total += 1
        m = self._metrics
        if m is not None:
            m.note_request(tenant, req_class, error=error, breach=breach)

    # -- burn rates ----------------------------------------------------------
    def _window_locked(self, ring: deque, now: float,
                       window_s: float) -> Dict[str, float]:
        cutoff = now - window_s
        n = errors = breaches = 0
        for t, err, br in ring:
            if t < cutoff:
                continue
            n += 1
            errors += err
            breaches += br
        avail_budget = 1.0 - self.availability_objective
        lat_budget = 1.0 - self.latency_target
        return {"requests": n, "errors": errors, "breaches": breaches,
                "availability_burn":
                    (errors / n) / avail_budget if n else 0.0,
                "latency_burn":
                    (breaches / n) / lat_budget if n else 0.0}

    def burn_rates(self) -> Dict[str, Dict[str, Dict[str, dict]]]:
        """``{tenant: {request_class: {"fast": {...}, "slow": {...}}}}``
        with per-window request/error counts and both burn rates —
        the fleetz/debugz document."""
        now = float(self._clock())
        out: Dict[str, Dict[str, Dict[str, dict]]] = {}
        with self._lock:
            keys = list(self._events.items())
        for (tenant, req_class), ring in keys:
            with self._lock:
                # prune anything older than the slow window so a
                # long-lived tracker's memory tracks traffic, not uptime
                cutoff = now - self.slow_window_s
                while ring and ring[0][0] < cutoff:
                    ring.popleft()
                fast = self._window_locked(ring, now, self.fast_window_s)
                slow = self._window_locked(ring, now, self.slow_window_s)
            out.setdefault(tenant, {})[req_class] = {"fast": fast,
                                                     "slow": slow}
        return out

    def scale_signal(self) -> float:
        """The autoscaler's secondary trigger: the worst fast-window
        burn rate (availability or latency) over NON-batch classes.
        Batch is excluded by construction — deferrable work must not
        scale the fleet (the queue-wait-EWMA discipline)."""
        worst = 0.0
        for tenant_rates in self.burn_rates().values():
            for req_class, windows in tenant_rates.items():
                if req_class == "batch":
                    continue
                fast = windows["fast"]
                worst = max(worst, fast["availability_burn"],
                            fast["latency_burn"])
        return worst

    # -- export --------------------------------------------------------------
    def export(self) -> Dict[str, Dict[str, Dict[str, dict]]]:
        """Refresh the ``_slo_*`` burn gauges (when ``metrics`` is
        armed) and return the burn-rate document — call from the
        scrape/fleetz path."""
        rates = self.burn_rates()
        m = self._metrics
        if m is not None:
            for tenant, per_class in rates.items():
                for req_class, windows in per_class.items():
                    for window, vals in windows.items():
                        m.set_burn(tenant, req_class, window,
                                   vals["availability_burn"],
                                   vals["latency_burn"])
        return rates

    def snapshot(self) -> Dict[str, Any]:
        """Objectives + current burn document (debugz/fleetz section)."""
        return {"availability_objective": self.availability_objective,
                "latency_objective_s": self.latency_objective_s,
                "latency_target": self.latency_target,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "observed_total": self.observed_total,
                "tenants_dropped": self.tenants_dropped,
                "burn_rates": self.burn_rates()}
