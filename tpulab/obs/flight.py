"""Flight recorder: one tail-sampled *wide event* per request.

Aggregate telemetry (tpulab.utils.metrics) answers "how is the fleet
doing"; it cannot answer the operator's p99 question — "why was THIS
request slow?".  The flight recorder answers it the way wide-event
systems do: every request assembles ONE structured record at completion
(tenant/model/priority, admission verdict + queue wait + DRR deficit,
lane, peak pages, dispatched block sizes, speculative acceptance, KV
swap events, HBM pressure rounds overlapping the request, chaos trips,
outcome, and the phase timings queue/prefill/TTFT/ITL/e2e), and a
**tail-based retention** policy decides which records survive the
bounded ring:

- errors (any non-SUCCESS outcome), DEADLINE_EXCEEDED and
  RESOURCE_EXHAUSTED outcomes, stalled streams, and requests a chaos
  rule fired during are ALWAYS kept (the ``tail`` ring);
- the rolling slowest requests are kept as **p99 exemplars**: an e2e
  strictly above the p99 of the recent-window reservoir qualifies;
- everything else is uniformly sampled (1 in ``sample_every``) into the
  ``uniform`` ring; the rest are counted, not stored.

Both rings are bounded deques, so a long-running server holds a recent
window of exactly the records an operator would have asked for.  The
retained set dumps as JSONL (one event per line — the grep/duckdb
surface) and as a Chrome trace of the exemplars' phase spans via the
existing :class:`~tpulab.utils.tracing.ChromeTraceRecorder`.

Disarmed cost: the serving path pays one ``is None`` branch per request
(the trace-recorder contract).  Armed, record assembly is a few dict
writes per request plus one classify at completion —
:meth:`FlightRecorder.assembly_quantiles` reports the measured cost and
the bench ``obs_overhead`` row enforces the <5% budget.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "KEEP_REASONS"]

#: retention classes, in decision order (the ``keep`` field of every
#: retained record; ``sampled`` marks the uniform survivors)
KEEP_REASONS = ("error", "deadline", "overload", "stall", "chaos", "slow",
                "sampled")

#: outcomes that classify as always-keep (next to the generic non-SUCCESS
#: "error" class) — the StatusCode names the RPC layer reports
_DEADLINE_OUTCOMES = ("DEADLINE_EXCEEDED",)
_OVERLOAD_OUTCOMES = ("RESOURCE_EXHAUSTED",)


class FlightRecorder:
    """Bounded, tail-retaining ring of per-request wide events.

    ``tail_capacity`` bounds the always-keep ring (errors/stalls/chaos/
    slow exemplars), ``uniform_capacity`` the sampled-baseline ring;
    ``sample_every`` is the uniform keep rate (every Nth healthy,
    unexceptional request — deterministic counter, no RNG: replaying a
    trace retains the same records).  ``p99_window`` sizes the rolling
    e2e reservoir behind the slowest-exemplar classifier and
    ``p99_min_n`` is the observation floor below which nothing
    classifies as slow (a cold reservoir must not call the first request
    an exemplar).
    """

    def __init__(self, tail_capacity: int = 256,
                 uniform_capacity: int = 256, sample_every: int = 16,
                 p99_window: int = 512, p99_min_n: int = 16):
        if tail_capacity < 1 or uniform_capacity < 1:
            raise ValueError("ring capacities must be >= 1")
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.sample_every = int(sample_every)
        self.p99_min_n = int(p99_min_n)
        self._tail: deque = deque(maxlen=int(tail_capacity))
        self._uniform: deque = deque(maxlen=int(uniform_capacity))
        self._e2e = deque(maxlen=int(p99_window))  # rolling e2e reservoir
        self._lock = threading.Lock()
        self._seq = 0            # record ids (monotonic)
        self._uniform_seen = 0   # healthy records offered to the sampler
        #: observability of the policy itself (test-assertable)
        self.observed_total = 0
        self.dropped_total = 0
        self.kept_by_reason: Dict[str, int] = {}
        #: record-assembly cost samples (seconds) — the obs_overhead
        #: bench row's p99 source
        self._assembly_s = deque(maxlen=2048)
        #: downstream consumers of the UNSAMPLED event stream
        #: (tpulab.obs.slo rides here) — see add_tap
        self._taps: List[Any] = []

    def add_tap(self, fn) -> None:
        """Subscribe ``fn(event)`` to every observed event BEFORE
        retention sampling — aggregating consumers (the SLO tracker)
        need the whole stream, not the tail-sampled survivors.  Taps
        run on the request-completion path: keep them cheap; exceptions
        are swallowed (a broken consumer must not fail requests)."""
        self._taps.append(fn)

    # -- ingestion -----------------------------------------------------------
    def observe(self, event: Dict[str, Any]) -> Optional[int]:
        """Classify + retain one completed request's wide event.

        The event is any flat-ish dict; the recorder reads (all
        optional): ``outcome`` (StatusCode name, default "SUCCESS"),
        ``stalled`` (bool), ``chaos_trips`` (dict of point -> fires
        during the request), ``e2e_s`` (float).  It stamps ``id``,
        ``keep`` (the retention reason) and ``wall_time`` onto retained
        events and returns the record id (None = uniformly dropped)."""
        t0 = time.perf_counter()
        for tap in tuple(self._taps):
            try:
                tap(event)
            except Exception:  # noqa: BLE001 - consumers must not fail us
                pass
        outcome = str(event.get("outcome", "SUCCESS") or "SUCCESS")
        e2e = event.get("e2e_s")
        with self._lock:
            self._seq += 1
            rec_id = self._seq
            self.observed_total += 1
            reason = self._classify_locked(outcome, event, e2e)
            if e2e is not None:
                # the reservoir sees every completed request (kept or
                # not) AFTER classification: a burst of slow requests
                # raises the bar for the next one, never for itself
                self._e2e.append(float(e2e))
            if reason is None:
                self.dropped_total += 1
                self._assembly_s.append(time.perf_counter() - t0)
                return None
            event = dict(event)
            event["id"] = rec_id
            event["keep"] = reason
            event.setdefault("wall_time", time.time())
            self.kept_by_reason[reason] = (
                self.kept_by_reason.get(reason, 0) + 1)
            ring = self._uniform if reason == "sampled" else self._tail
            if len(ring) == ring.maxlen:
                self.dropped_total += 1  # the ring's oldest falls off
            ring.append(event)
            self._assembly_s.append(time.perf_counter() - t0)
            return rec_id

    def _classify_locked(self, outcome: str, event: Dict[str, Any],
                         e2e) -> Optional[str]:
        """Retention decision (module docstring order); None = drop."""
        if outcome in _DEADLINE_OUTCOMES:
            return "deadline"
        if outcome in _OVERLOAD_OUTCOMES:
            return "overload"
        if outcome not in ("SUCCESS", "", None):
            return "error"
        if event.get("stalled"):
            return "stall"
        if event.get("chaos_trips"):
            return "chaos"
        if (e2e is not None and len(self._e2e) >= self.p99_min_n
                and float(e2e) > self._p99_locked()):
            # STRICTLY above the rolling p99: homogeneous traffic (every
            # e2e equal to the quantile) must stay uniformly sampled,
            # not all classify as exemplars
            return "slow"
        self._uniform_seen += 1
        if (self._uniform_seen - 1) % self.sample_every == 0:
            return "sampled"
        return None

    def _p99_locked(self) -> float:
        vals = sorted(self._e2e)
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    # -- views ---------------------------------------------------------------
    def records(self, keep: Optional[str] = None) -> List[Dict[str, Any]]:
        """Retained wide events in id order (optionally one retention
        class); copies — callers may mutate freely."""
        with self._lock:
            out = list(self._tail) + list(self._uniform)
        out.sort(key=lambda r: r["id"])
        if keep is not None:
            out = [r for r in out if r["keep"] == keep]
        return [dict(r) for r in out]

    def exemplar_ids(self, limit: int = 32) -> List[int]:
        """Most recent always-keep record ids (the debugz pointer: an
        operator follows these into the JSONL dump)."""
        with self._lock:
            ids = [r["id"] for r in self._tail]
        return ids[-limit:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._tail) + len(self._uniform)

    def assembly_quantiles(self) -> Dict[str, float]:
        """p50/p99 of observed record-assembly cost in seconds."""
        with self._lock:
            vals = sorted(self._assembly_s)
        if not vals:
            return {"p50": 0.0, "p99": 0.0}
        return {"p50": vals[len(vals) // 2],
                "p99": vals[min(len(vals) - 1, int(0.99 * len(vals)))]}

    # -- dumps ---------------------------------------------------------------
    def dump_jsonl(self, path: str) -> int:
        """Write the retained events as JSONL (atomic tmp+rename, the
        recorder-save contract); returns the record count."""
        import os
        recs = self.records()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for r in recs:
                f.write(json.dumps(r, default=str) + "\n")
        os.replace(tmp, path)
        return len(recs)

    def save_chrome_trace(self, path: str,
                          keep: Optional[str] = None) -> int:
        """Render retained exemplars' phase timings as a Chrome trace via
        the existing recorder (one row per record; spans queue_wait /
        prefill / decode tagged with id/tenant/model/outcome) — load it
        in ui.perfetto.dev next to a merged request-trace timeline.
        Returns the number of records rendered."""
        from tpulab.utils.tracing import ChromeTraceRecorder
        rec = ChromeTraceRecorder(process_name="flight-recorder")
        n = 0
        for r in self.records(keep=keep):
            t0 = r.get("t_submit")
            if t0 is None:
                continue
            n += 1
            args = {k: r[k] for k in ("id", "keep", "tenant", "model",
                                      "outcome", "trace_id")
                    if r.get(k) is not None}
            tid = r.get("id", 0)
            pf0 = r.get("t_prefill0")
            tf = r.get("t_first")
            tl = r.get("t_last")
            if pf0 is not None:
                rec.add_span("queue_wait", t0, pf0 - t0, tid=tid, **args)
            if pf0 is not None and tf is not None:
                rec.add_span("prefill", pf0, max(0.0, tf - pf0), tid=tid,
                             **args)
            if tf is not None and tl is not None and tl > tf:
                rec.add_span("decode", tf, tl - tf, tid=tid,
                             tokens=r.get("tokens"), **args)
            e2e = r.get("e2e_s")
            if e2e is not None:
                rec.add_span("request", t0, e2e, tid=tid, **args)
        rec.save(path)
        return n
