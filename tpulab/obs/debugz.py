"""Debugz: the live "what is the engine holding RIGHT NOW" snapshot.

Metrics are rates and distributions; traces are the past; this is the
**present tense** — the view an operator pulls when a replica looks
wedged: which requests occupy which batcher lanes (and for how long),
where the elastic pool sits on its compile-shape ladder, who holds the
HBM ledger's bytes (and whether the ledger still agrees with the
allocator gauges), which models are resident and how many leases pin
them, how deep each tenant's admission queue runs, what chaos is armed,
and which flight-recorder exemplars to read next.

Served over the ``Debug`` unary RPC (``RemoteInferenceManager.debugz``)
as ONE JSON document: debugz's shape tracks engine internals every PR,
so it deliberately stays out of the proto schema (DebugResponse carries
``snapshot_json``).  Document layout (all sections optional — a replica
only reports the subsystems it runs):

    {"wall_time": ..., "server_version": ...,
     "engines": {name: {"lanes": [...], "queue": {...}, "pool": {...},
                        "dispatch": {...}, "spec": {...},
                        "prefix_cache": {...}}},
     "admission": {"inflight", "queue_depth", "queue_depths_by_tenant",
                   "model_inflight", "admitted_total", ...},
     "hbm": {"capacity_bytes", "free_hbm_bytes", "claims": [...],
             "reservations": [...], "verify_mismatches": {...}, ...},
     "modelstore": {"resident", "host", "leases": {...}},
     "chaos": {"armed", "rules", "fired", "seen"},
     "watchdog": {...},
     "fleet": {"election": {...}, "supervisor": {...},
               "autoscaler": {...}},
     "flight": {"retained", "dropped", "kept_by_reason",
                "exemplar_ids", "assembly_ms_p99"}}

On-demand profiling: ``profile_ticks=N`` on the Debug RPC arms
``jax.profiler`` around the next N scheduler ticks of the selected
engine (:meth:`~tpulab.engine.paged.ContinuousBatcher.arm_profile`) and
the response returns the trace directory — ``tensorboard --logdir`` it.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

__all__ = ["debug_snapshot"]


def _engine_section(engine) -> Dict[str, Any]:
    """One generation engine's live state; engines without the batcher's
    introspection surface report what they expose."""
    state = getattr(engine, "debug_state", None)
    if callable(state):
        return state()
    out: Dict[str, Any] = {"kind": type(engine).__name__}
    for attr in ("queued_requests", "active_lanes", "vocab", "max_len"):
        v = getattr(engine, attr, None)
        if v is not None:
            try:
                out[attr] = int(v)
            except Exception:
                pass
    return out


def debug_snapshot(resources=None, *, generation_engines=None,
                   admission=None, hbm=None, modelstore=None,
                   flight=None, watchdog=None, fleet=None,
                   model_name: str = "") -> Dict[str, Any]:
    """Assemble the live snapshot (module docstring layout).

    Pass an :class:`~tpulab.rpc.infer_service.InferResources` (the Debug
    RPC does) or the subsystems explicitly (engine-level use: tests,
    bench, a REPL poking a live process).  ``model_name`` focuses the
    engines section on one engine; unknown names report an empty engines
    map (the RPC layer turns that into UNKNOWN_MODEL)."""
    if resources is not None:
        generation_engines = (generation_engines
                              or getattr(resources, "generation_engines",
                                         None))
        admission = admission or getattr(resources, "admission", None)
        hbm = hbm or getattr(resources, "hbm", None)
        modelstore = modelstore or getattr(resources, "modelstore", None)
        flight = flight or getattr(resources, "flight", None)
        watchdog = watchdog or getattr(resources, "watchdog", None)
        fleet = fleet or getattr(resources, "fleet", None)
    snap: Dict[str, Any] = {"wall_time": time.time()}

    engines = {}
    for name, eng in (generation_engines or {}).items():
        if model_name and name != model_name:
            continue
        try:
            engines[name] = _engine_section(eng)
        except Exception as e:  # a torn-down engine must not kill debugz
            engines[name] = {"error": f"{type(e).__name__}: {e}"}
    snap["engines"] = engines

    if admission is not None:
        try:
            snap["admission"] = {
                "inflight": admission.inflight,
                "queue_depth": admission.queue_depth,
                # offline batch lane: its waiters ride their own queue
                # (never an online queue slot) — reported separately,
                # and batch tenants appear as "batch:<tenant>" below
                "batch_queue_depth": getattr(admission,
                                             "batch_queue_depth", 0),
                "batch_admitted_total": getattr(admission,
                                                "batch_admitted_total", 0),
                "queue_depths_by_tenant": admission.queue_depths(),
                "model_inflight": dict(admission.model_inflight),
                "admitted_total": admission.admitted_total,
                "rejected_total": admission.rejected_total,
                "rejected_by_reason": dict(admission.rejected_by_reason),
                "shed_total": admission.shed_total,
                "peak_queue_depth": admission.peak_queue_depth,
            }
        except Exception as e:
            snap["admission"] = {"error": f"{type(e).__name__}: {e}"}

    if hbm is not None:
        try:
            ledger = hbm.ledger
            snap["hbm"] = {
                "capacity_bytes": int(hbm.capacity_bytes),
                "free_hbm_bytes": int(hbm.free_hbm_bytes),
                # claims serialize as [tenant, str(tag), bytes] — tags
                # are hashables (tuples), JSON wants strings
                "claims": [[t, str(tag), int(n)]
                           for t, tag, n in ledger.claims()],
                "reservations": hbm.reservations(),
                # the honesty check debugz exists to surface: {} = the
                # ledger agrees byte-for-byte with every live gauge
                "verify_mismatches": {t: [int(c), int(g)]
                                      for t, (c, g) in
                                      hbm.verify().items()},
                "pressure_events": hbm.pressure_events,
                "grants": hbm.grants,
                "denials": hbm.denials,
                "demotions_forced": hbm.demotions_forced,
                "evictions_forced": hbm.evictions_forced,
            }
        except Exception as e:
            snap["hbm"] = {"error": f"{type(e).__name__}: {e}"}

    if modelstore is not None:
        try:
            snap["modelstore"] = {
                "resident": modelstore.resident_models(),
                "host": modelstore.host_models(),
                "leases": modelstore.lease_counts(),
            }
        except Exception as e:
            snap["modelstore"] = {"error": f"{type(e).__name__}: {e}"}

    from tpulab import chaos
    sched = chaos.armed()
    snap["chaos"] = {"armed": sched is not None}
    if sched is not None:
        snap["chaos"].update({
            "rules": [repr(r) for r in sched.rules],
            "seed": sched.seed,
            "fired": sched.fired_snapshot(),
            "seen": sched.seen_snapshot(),
        })

    if watchdog is not None:
        try:
            snap["watchdog"] = {"healthy": bool(watchdog.healthy)}
        except Exception:
            pass

    if fleet is not None:
        # control-plane state (tpulab.fleet.control.FleetController —
        # or anything with .snapshot()): election + supervision +
        # autoscaling, the "who leads / what died / what's draining"
        # answers an operator pulls during fleet churn
        try:
            snap["fleet"] = fleet.snapshot()
        except Exception as e:
            snap["fleet"] = {"error": f"{type(e).__name__}: {e}"}

    if flight is not None:
        aq = flight.assembly_quantiles()
        snap["flight"] = {
            "retained": len(flight),
            "observed_total": flight.observed_total,
            "dropped_total": flight.dropped_total,
            "kept_by_reason": dict(flight.kept_by_reason),
            "exemplar_ids": flight.exemplar_ids(),
            "assembly_ms_p50": round(aq["p50"] * 1e3, 4),
            "assembly_ms_p99": round(aq["p99"] * 1e3, 4),
        }
    return snap


def arm_profile(generation_engines: Optional[Dict[str, Any]],
                model_name: str, ticks: int,
                log_dir: str = "") -> str:
    """Arm an XLA profiler capture around the next ``ticks`` scheduler
    ticks of the selected engine (``model_name`` empty = the first
    profile-capable engine).  Returns the trace directory; raises
    KeyError when no engine can capture."""
    for name, eng in (generation_engines or {}).items():
        if model_name and name != model_name:
            continue
        armer = getattr(eng, "arm_profile", None)
        if callable(armer):
            return armer(int(ticks), log_dir or None)
    raise KeyError(model_name or "<any>")
