"""Control-plane event journal: every fleet decision, durably, in order.

Metrics count WHAT the control plane did; the journal records WHY, with
the evidence, in a form that survives the process that wrote it.  Every
supervisor classification (death with its evidence — exit code vs probe
streak — respawn, backoff, quarantine), every election transition
(acquire/lost/resign/fenced, stamped with the fencing token), and every
autoscaler decision (scale up/down with the queue-wait/overload/SLO-burn
evidence, drain start/complete/timeout) appends ONE structured JSONL
event.  "Why did the fleet do that?" is then a grep over one file, after
any crash — including the crash of the node that wrote it.

Durability model (the tpulab.batch.job JSONL sink's, shared):

- **append-only**: events are one ``json.dumps`` line each, written with
  a single ``write()`` + ``flush()`` under a lock.  A crash mid-append
  can tear at most the TRAILING line.
- **torn-write-tolerant replay**: :func:`replay_journal` skips unparsable
  lines (``except ValueError: continue``) — the same leniency the batch
  checkpoint loader applies — so a journal torn by SIGKILL replays
  cleanly up to the last durable event.
- **monotonic per-writer sequence**: every event carries ``seq`` (and
  the writing ``node``); a journal reopened after a crash resumes its
  sequence from the replayed maximum, so one lineage of a control node
  produces one gap-free sequence.  :func:`sequence_gaps` audits it.

This module is deliberately **stdlib-only** (like tpulab.fleet.election):
a control process can load it by path without importing — or paying
for — the serving stack.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("tpulab.obs")

__all__ = ["EventJournal", "replay_journal", "sequence_gaps"]


def replay_journal(path: str) -> List[Dict[str, Any]]:
    """Read a journal back as a list of event dicts, in file order.

    Tolerates a missing file (``[]`` — the journal was never armed) and
    torn trailing writes (a line SIGKILL cut mid-``write`` parses as
    garbage and is skipped, like the batch sink's checkpoint loader)."""
    events: List[Dict[str, Any]] = []
    try:
        f = open(path, "r", encoding="utf-8")
    except OSError:
        return events
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # torn trailing write — replay what is durable
            if isinstance(ev, dict):
                events.append(ev)
    return events


def sequence_gaps(
        events: List[Dict[str, Any]]) -> List[Tuple[str, int, int]]:
    """Audit per-writer sequence continuity: returns ``(node, seen_seq,
    expected_seq)`` for every event whose ``seq`` is not exactly one
    past its node's previous event.  An empty list is the no-loss
    proof the takeover acceptance test asserts."""
    last: Dict[str, int] = {}
    gaps: List[Tuple[str, int, int]] = []
    for ev in events:
        node = str(ev.get("node", ""))
        seq = int(ev.get("seq", 0))
        prev = last.get(node)
        if prev is not None and seq != prev + 1:
            gaps.append((node, seq, prev + 1))
        last[node] = seq
    return gaps


class EventJournal:
    """Crash-safe append-only JSONL event sink (module docstring).

    ``record(kind, **fields)`` stamps ``seq``/``node``/``wall_time`` and
    appends one line; IO failures are swallowed and counted
    (``append_errors``) — the journal observes the control plane, it
    must never take it down.  ``clock`` is injectable for deterministic
    tests; ``fsync=True`` pays one fsync per event for power-loss
    durability (crash durability — the mode every test and the takeover
    acceptance run in — needs only the flush)."""

    def __init__(self, path: str, node: Optional[str] = None,
                 clock=time.time, fsync: bool = False):
        self.path = path
        self.node = node or f"{os.uname().nodename}:{os.getpid()}"
        self._clock = clock
        self._fsync = bool(fsync)
        self._lock = threading.Lock()
        self._f = None
        # a reopened journal continues its lineage's sequence: the
        # crash-restart of a control node must not reset seq to 0 (a
        # reset would read as a gap — or worse, as silent overwrite)
        self._seq = 0
        for ev in replay_journal(path):
            if str(ev.get("node", "")) == self.node:
                self._seq = max(self._seq, int(ev.get("seq", 0)))
        #: observability of the journal itself
        self.events_written = 0
        self.append_errors = 0
        self._append_s: deque = deque(maxlen=2048)

    # -- ingestion -----------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> Optional[Dict[str, Any]]:
        """Append one event; returns the stamped event dict (None when
        the append failed — counted, logged once per failure)."""
        t0 = time.perf_counter()
        with self._lock:
            self._seq += 1
            ev: Dict[str, Any] = {"seq": self._seq, "kind": str(kind),
                                  "node": self.node,
                                  "wall_time": round(float(self._clock()),
                                                     6)}
            ev.update(fields)
            try:
                if self._f is None:
                    self._f = open(self.path, "a", encoding="utf-8")
                self._f.write(json.dumps(ev, default=str,
                                         separators=(",", ":")) + "\n")
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
            except (OSError, ValueError):
                self.append_errors += 1
                log.exception("journal append failed (%s)", self.path)
                return None
            self.events_written += 1
            self._append_s.append(time.perf_counter() - t0)
            return ev

    # -- views ---------------------------------------------------------------
    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """Replay this journal's file (all writers, torn-tolerant),
        optionally filtered to one event kind."""
        with self._lock:
            if self._f is not None:
                self._f.flush()
        evs = replay_journal(self.path)
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        return evs

    def append_quantiles(self) -> Dict[str, float]:
        """p50/p99 of measured append cost in seconds — the bench
        ``fleet_obs`` row's journal-cost source."""
        with self._lock:
            vals = sorted(self._append_s)
        if not vals:
            return {"p50": 0.0, "p99": 0.0}
        return {"p50": vals[len(vals) // 2],
                "p99": vals[min(len(vals) - 1, int(0.99 * len(vals)))]}

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:  # pragma: no cover - teardown best-effort
                pass

    def __enter__(self) -> "EventJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
