"""tpulab.obs — per-request wide events + live engine introspection.

The two views aggregate telemetry (PR 2's metrics/traces) cannot give:

- :class:`FlightRecorder` (flight.py): ONE structured wide event per
  request, tail-sampled — errors, deadline/overload outcomes, stalls,
  chaos-hit requests and the rolling slowest-p99 exemplars always
  survive the bounded ring; healthy traffic is uniformly sampled.
  Answers "why was THIS request slow" from the record, not a regex over
  logs.
- :func:`debug_snapshot` (debugz.py): the live "what is the engine
  holding right now" document — lanes, elastic pool ladder position,
  HBM ledger claims + verify, modelstore leases, admission queue depths,
  chaos armament, flight exemplar pointers — served over the ``Debug``
  RPC with on-demand XLA profiler capture.
- :class:`EventJournal` (journal.py): the control plane's crash-safe
  append-only JSONL decision log — deaths with evidence, election
  transitions with fencing tokens, autoscaler actions with their
  signals; :func:`replay_journal` reads it back torn-write-tolerantly.
- :class:`SLOTracker` (slo.py): per-tenant availability/latency error
  budgets over fast+slow burn-rate windows, fed from the flight-event
  stream (``flight.add_tap``); exports ``_slo_*`` gauges and the
  autoscaler's optional secondary scale-up signal.

See docs/OBSERVABILITY.md ("Flight recorder", "Debugz", "Fleet
observability").
"""

from tpulab.obs.bench import benchmark_obs_overhead  # noqa: F401
from tpulab.obs.debugz import arm_profile, debug_snapshot  # noqa: F401
from tpulab.obs.flight import KEEP_REASONS, FlightRecorder  # noqa: F401
from tpulab.obs.journal import (EventJournal, replay_journal,  # noqa: F401
                                sequence_gaps)
from tpulab.obs.slo import SLOTracker  # noqa: F401

__all__ = ["FlightRecorder", "KEEP_REASONS", "debug_snapshot",
           "arm_profile", "benchmark_obs_overhead", "EventJournal",
           "replay_journal", "sequence_gaps", "SLOTracker"]
