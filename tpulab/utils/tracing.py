"""Tracing/profiling hooks (SURVEY §5 aux subsystems: the reference has
per-stage cudaEvent timing + Walltime + InferBench metrics; the TPU
equivalent adds the XLA profiler).

- :func:`trace` / :func:`annotate` — wrap jax.profiler: capture a
  TensorBoard-loadable trace of the serving hot path, with named regions
  (the nvtx-range analog the reference lacked).
- :class:`StageTimer` — the TimedBenchmarkWorkspace pattern as a reusable
  context: named stage durations with blocking sync at boundaries.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/tpulab-trace"):
    """Capture an XLA profiler trace around a block::

        with tracing.trace("/tmp/trace"):
            runner.infer(**arrays).result()
        # -> tensorboard --logdir /tmp/trace
    """
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a trace (nvtx-range analog)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


class StageTimer:
    """Named stage timing (the reference's cudaEvent H2D/compute/D2H split,
    generalized).  JAX dispatch is async, so each stage that launches device
    work MUST name a ``sync_on`` target — otherwise the stage records only
    dispatch time and its device time bleeds into the next stage::

        t = StageTimer()
        holder = {}
        with t.stage("h2d"):
            holder["dev"] = copy_to_device(host)
        t.sync("h2d", holder["dev"])              # or stage(..., sync_on=...)
        with t.stage("compute", sync_on_fn=lambda: out):
            out = compiled(holder["dev"])
        t.stages_ms  # {"h2d": ..., "compute": ...}
    """

    def __init__(self):
        self.stages_ms: Dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str, sync_on=None, sync_on_fn=None):
        t0 = time.perf_counter()
        yield
        target = sync_on_fn() if sync_on_fn is not None else sync_on
        if target is not None:
            import jax
            jax.block_until_ready(target)
        self.stages_ms[name] = self.stages_ms.get(name, 0.0) + \
            (time.perf_counter() - t0) * 1e3

    def sync(self, name: str, target) -> None:
        """Fold a late device sync into an already-recorded stage."""
        import jax
        t0 = time.perf_counter()
        jax.block_until_ready(target)
        self.stages_ms[name] = self.stages_ms.get(name, 0.0) + \
            (time.perf_counter() - t0) * 1e3

    @property
    def total_ms(self) -> float:
        return sum(self.stages_ms.values())


class ChromeTraceRecorder:
    """Host-side request-lifecycle trace in Chrome trace-event format
    (load in chrome://tracing or ui.perfetto.dev) — the chrome-trace
    tooling SURVEY §5 notes the reference lacked.

    The serving path (``build_infer_service(trace=recorder)``) records one
    span per request stage (batch_wait / pipeline / respond) on the
    handling thread's row; ``save()`` writes the JSON trace.  Collection
    is thread-safe and bounded (a ring of ``max_events`` — a long-running
    server keeps the most recent window rather than growing without
    limit)."""

    def __init__(self, max_events: int = 100_000):
        import collections
        self._events = collections.deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._pid = os.getpid()

    def add_span(self, name: str, start_s: float, dur_s: float,
                 tid: Optional[int] = None, **args) -> None:
        """One complete ('X') event; ``start_s`` is a time.perf_counter
        value from the same process."""
        ev = {"name": name, "ph": "X", "pid": self._pid,
              "tid": tid if tid is not None else threading.get_ident(),
              "ts": round((start_s - self._t0) * 1e6, 3),
              "dur": round(dur_s * 1e6, 3)}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def save(self, path: str) -> str:
        import json
        with self._lock:
            events = list(self._events)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path
