"""Tracing/profiling hooks (SURVEY §5 aux subsystems: the reference has
per-stage cudaEvent timing + Walltime + InferBench metrics; the TPU
equivalent adds the XLA profiler).

- :func:`trace` / :func:`annotate` — wrap jax.profiler: capture a
  TensorBoard-loadable trace of the serving hot path, with named regions
  (the nvtx-range analog the reference lacked).
- :class:`StageTimer` — the TimedBenchmarkWorkspace pattern as a reusable
  context: named stage durations with blocking sync at boundaries.
- :class:`TraceContext` / :class:`ChromeTraceRecorder` /
  :func:`merge_chrome_traces` — request-scoped distributed tracing: the
  client mints a trace id, carries it over gRPC (request field + metadata),
  both processes tag their spans with it, and the saved traces merge into
  ONE chrome://tracing / perfetto timeline (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterable, Optional

#: gRPC metadata key carrying the trace id (request-field carriage is the
#: primary channel; the metadata rides along for middleboxes/interceptors
#: that never parse the payload)
TRACE_METADATA_KEY = "tpulab-trace-id"


def mint_trace_id() -> str:
    """16-hex request-scoped trace id (random; no coordination needed)."""
    import uuid
    return uuid.uuid4().hex[:16]


class TraceContext:
    """One request's trace identity, propagated client -> server.

    The client mints it once per logical request (NOT per attempt — a
    failover replay keeps the id, so all attempts line up under one
    request in the merged timeline); servers recover it from the request
    message's ``trace_id`` field or the ``tpulab-trace-id`` gRPC metadata.
    """

    __slots__ = ("trace_id",)

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or mint_trace_id()

    def metadata(self) -> tuple:
        """gRPC call metadata carrying this context."""
        return ((TRACE_METADATA_KEY, self.trace_id),)

    @classmethod
    def from_metadata(cls, metadata: Optional[Iterable]) -> Optional["TraceContext"]:
        """Parse from an iterable of (key, value) pairs; None when absent."""
        for k, v in metadata or ():
            if k == TRACE_METADATA_KEY and v:
                return cls(str(v))
        return None

    @classmethod
    def of_request(cls, request, grpc_context=None) -> Optional["TraceContext"]:
        """Server-side recovery: the request's ``trace_id`` field first,
        else the invocation metadata; None for untraced requests."""
        rid = getattr(request, "trace_id", "")
        if rid:
            return cls(rid)
        if grpc_context is not None and hasattr(grpc_context,
                                                "invocation_metadata"):
            try:
                return cls.from_metadata(grpc_context.invocation_metadata())
            except Exception:  # pragma: no cover - exotic grpc shims
                return None
        return None

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id})"


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/tpulab-trace"):
    """Capture an XLA profiler trace around a block::

        with tracing.trace("/tmp/trace"):
            runner.infer(**arrays).result()
        # -> tensorboard --logdir /tmp/trace
    """
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a trace (nvtx-range analog)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


class StageTimer:
    """Named stage timing (the reference's cudaEvent H2D/compute/D2H split,
    generalized).  JAX dispatch is async, so each stage that launches device
    work MUST name a ``sync_on`` target — otherwise the stage records only
    dispatch time and its device time bleeds into the next stage::

        t = StageTimer()
        holder = {}
        with t.stage("h2d"):
            holder["dev"] = copy_to_device(host)
        t.sync("h2d", holder["dev"])              # or stage(..., sync_on=...)
        with t.stage("compute", sync_on_fn=lambda: out):
            out = compiled(holder["dev"])
        t.stages_ms  # {"h2d": ..., "compute": ...}
    """

    def __init__(self):
        self.stages_ms: Dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str, sync_on=None, sync_on_fn=None):
        t0 = time.perf_counter()
        yield
        target = sync_on_fn() if sync_on_fn is not None else sync_on
        if target is not None:
            import jax
            jax.block_until_ready(target)
        self.stages_ms[name] = self.stages_ms.get(name, 0.0) + \
            (time.perf_counter() - t0) * 1e3

    def sync(self, name: str, target) -> None:
        """Fold a late device sync into an already-recorded stage."""
        import jax
        t0 = time.perf_counter()
        jax.block_until_ready(target)
        self.stages_ms[name] = self.stages_ms.get(name, 0.0) + \
            (time.perf_counter() - t0) * 1e3

    @property
    def total_ms(self) -> float:
        return sum(self.stages_ms.values())


class ChromeTraceRecorder:
    """Host-side request-lifecycle trace in Chrome trace-event format
    (load in chrome://tracing or ui.perfetto.dev) — the chrome-trace
    tooling SURVEY §5 notes the reference lacked.

    The serving path (``build_infer_service(trace=recorder)``) records one
    span per request stage (batch_wait / pipeline / respond) on the
    handling thread's row; ``save()`` writes the JSON trace.  Collection
    is thread-safe and bounded (a ring of ``max_events`` — a long-running
    server keeps the most recent window rather than growing without
    limit)."""

    def __init__(self, max_events: int = 100_000,
                 process_name: Optional[str] = None):
        import collections
        self._events = collections.deque(maxlen=max_events)
        self._lock = threading.Lock()
        #: events the ring has discarded (oldest-first) to stay bounded —
        #: a saved trace that silently lost its head reads as "the server
        #: was idle before this window", so the drop count rides save()'s
        #: otherData and the first drop warns once
        self.dropped_events = 0
        self._warned_drop = False
        # paired clock anchor: _epoch0 is the wall-clock instant at which
        # perf_counter read _t0.  Event ts stay perf_counter-relative (sub-
        # microsecond deltas within the process); the anchor rides the
        # saved file so merge_chrome_traces can re-base traces from
        # DIFFERENT processes onto one wall-clock axis.
        self._t0 = time.perf_counter()
        self._epoch0 = time.time()
        self._pid = os.getpid()
        self.process_name = process_name

    def add_span(self, name: str, start_s: float, dur_s: float,
                 tid: Optional[int] = None, **args) -> None:
        """One complete ('X') event; ``start_s`` is a time.perf_counter
        value from the same process."""
        ev = {"name": name, "ph": "X", "pid": self._pid,
              "tid": tid if tid is not None else threading.get_ident(),
              "ts": round((start_s - self._t0) * 1e6, 3),
              "dur": round(dur_s * 1e6, 3)}
        if args:
            ev["args"] = args
        with self._lock:
            self._append_locked(ev)

    def _append_locked(self, ev: dict) -> None:
        """Ring append that COUNTS what the bounded deque would silently
        discard (deque(maxlen=N) drops the oldest event on overflow)."""
        if len(self._events) == self._events.maxlen:
            self.dropped_events += 1
            if not self._warned_drop:
                self._warned_drop = True
                import logging
                logging.getLogger("tpulab.tracing").warning(
                    "ChromeTraceRecorder ring full (max_events=%d): oldest "
                    "events are being dropped; saved traces carry the count "
                    "in otherData.dropped_events", self._events.maxlen)
        self._events.append(ev)

    def add_counter(self, name: str, ts_s: float, **values) -> None:
        """One counter ('C') sample; ``ts_s`` is a time.perf_counter value
        from the same process.  Perfetto/chrome render each name as a
        stacked counter track — the batcher samples ``decode_block``
        (tokens delivered + block size K per fused dispatch) so the
        tokens-per-dispatch shape is visible on the same timeline as the
        request spans it explains."""
        ev = {"name": name, "ph": "C", "pid": self._pid, "tid": 0,
              "ts": round((ts_s - self._t0) * 1e6, 3),
              "args": {k: float(v) for k, v in values.items()}}
        with self._lock:
            self._append_locked(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def save(self, path: str) -> str:
        """Atomic write (tmp + rename): a concurrent reader — e.g. the
        merge step polling another process's autosaved trace — never
        observes a torn JSON document."""
        import json
        with self._lock:
            events = list(self._events)
            dropped = self.dropped_events
        if self.process_name:
            events.insert(0, {"name": "process_name", "ph": "M",
                              "pid": self._pid, "tid": 0,
                              "args": {"name": self.process_name}})
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"epoch_origin_s": self._epoch0,
                             "pid": self._pid,
                             "dropped_events": dropped}}
        tmp = f"{path}.tmp.{self._pid}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


def merge_chrome_traces(out_path: str, *paths: str) -> str:
    """Merge per-process Chrome trace files into ONE timeline.

    Each input carries its recorder's ``epoch_origin_s`` anchor (wall
    clock at its events' ts=0); events are shifted by the anchor deltas so
    spans from different processes line up on one wall-clock axis (cross-
    machine accuracy = NTP skew — fine for the >=100us spans recorded
    here).  Events keep their pid, so perfetto shows one process track per
    input.  Metadata ('M') events pass through unshifted."""
    import json
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    origins = [float(d.get("otherData", {}).get("epoch_origin_s", 0.0))
               for d in docs]
    base = min((o for o in origins if o), default=0.0)
    merged = []
    for doc, origin in zip(docs, origins):
        shift_us = (origin - base) * 1e6 if origin else 0.0
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "M":
                ev = dict(ev, ts=round(ev.get("ts", 0.0) + shift_us, 3))
            merged.append(ev)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms",
                   "otherData": {"epoch_origin_s": base,
                                 "merged_from": len(docs)}}, f)
    return out_path
