"""Device watchdog: in-process failure detection.

The reference handles failure at the deployment layer (envoy health routing,
k8s liveness — SURVEY §5 'no in-process retry/failover').  tpulab keeps that
deployment posture (k8s probes hit the Health RPC) but adds the in-process
detector those probes need on TPU: a periodic *canary dispatch* (tiny compiled
program) that catches wedged runtimes — the failure mode where the process is
alive but the device/tunnel no longer completes work.

``DeviceWatchdog`` flips ``healthy`` when canaries stop completing within
their deadline; the Health RPC reports it, so k8s/envoy rotate the replica
out exactly as the reference's deployment assets expect.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("tpulab.utils")


class DeviceWatchdog:
    """Periodic canary dispatch with a completion deadline."""

    def __init__(self, device=None, period_s: float = 10.0,
                 deadline_s: float = 30.0,
                 on_unhealthy: Optional[Callable[[str], None]] = None):
        self.period_s = period_s
        self.deadline_s = deadline_s
        self._on_unhealthy = on_unhealthy
        self._device = device
        self._healthy = True
        self._last_ok: Optional[float] = None
        self._reason = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._canary = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DeviceWatchdog":
        import jax
        import jax.numpy as jnp
        from tpulab.tpu import platform as plat

        device = self._device if self._device is not None else plat.local_device(0)
        x = jax.device_put(jnp.ones((8, 8), jnp.float32), device)
        fn = jax.jit(lambda a: (a @ a).sum()).lower(x).compile()
        self._canary = (fn, x)
        self._thread = threading.Thread(target=self._run, name="watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- state --------------------------------------------------------------
    @property
    def healthy(self) -> bool:
        return self._healthy

    @property
    def reason(self) -> str:
        return self._reason

    @property
    def seconds_since_ok(self) -> Optional[float]:
        return None if self._last_ok is None else time.monotonic() - self._last_ok

    # -- loop ---------------------------------------------------------------
    _probe_thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            # a still-running probe means the device is still wedged — do
            # NOT stack another thread on it (unbounded leak otherwise)
            if self._probe_thread is not None and self._probe_thread.is_alive():
                self._mark_unhealthy(
                    f"canary still outstanding after {self.deadline_s}s+")
                continue
            fn, x = self._canary  # re-read: canaries are hot-swappable
            done = threading.Event()
            err = []

            def canary():
                try:
                    fn(x).block_until_ready()
                    done.set()
                except Exception as e:  # noqa: BLE001
                    err.append(e)
                    done.set()

            t = threading.Thread(target=canary, daemon=True)
            self._probe_thread = t
            t.start()
            if not done.wait(self.deadline_s) or err:
                self._mark_unhealthy(
                    f"canary error: {err[0]}" if err else
                    f"canary exceeded {self.deadline_s}s deadline")
            else:
                if not self._healthy:
                    log.warning("device recovered")
                self._healthy = True
                self._reason = ""
                self._last_ok = time.monotonic()

    def _mark_unhealthy(self, reason: str) -> None:
        self._reason = reason
        if self._healthy:
            log.error("device unhealthy: %s", reason)
            self._healthy = False
            if self._on_unhealthy is not None:
                try:
                    self._on_unhealthy(reason)
                except Exception:  # pragma: no cover
                    log.exception("on_unhealthy hook failed")
