"""Prometheus metrics (reference examples/02 metrics.h/cc:27-107 — singleton
Exposer+Registry; compute/request duration summaries with p50/p90/p99
quantiles; load-ratio histogram {1.25,1.5,2,10,100}; device power gauge
polled from Server::Run's control lambda).

prometheus_client has no quantile Summary, so the duration summaries are
implemented the way the reference's consumers read them: sliding-window
reservoirs exported as per-quantile gauges, next to total count/sum counters.
The NVML power gauge's TPU analog is the HBM usage gauge (polled from the
server control lambda via :meth:`InferenceMetrics.poll_device`).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

try:
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, start_http_server)
    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

#: reference load-ratio buckets (metrics.cc): request_time / compute_time
LOAD_RATIO_BUCKETS = (1.25, 1.5, 2.0, 10.0, 100.0)

_QUANTILES = (0.5, 0.9, 0.99)


class _Reservoir:
    """Sliding-window quantile reservoir backing a 'summary'."""

    def __init__(self, size: int = 2048):
        self._buf = np.zeros(size, np.float64)
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1

    def quantile(self, q: float) -> float:
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return 0.0
            return float(np.percentile(self._buf[:n], q * 100))


class InferenceMetrics:
    """The example-02 metric set for one service."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self._request = _Reservoir()
        self._compute = _Reservoir()
        self.request_count = Counter(
            f"{ns}_request_total", "Requests completed", registry=self.registry)
        # Gauges (not Counters) so the exported sample keeps the summary
        # convention `..._seconds_sum` — Counter would append `_total`.
        self.request_seconds_sum = Gauge(
            f"{ns}_request_duration_seconds_sum", "Total request seconds",
            registry=self.registry)
        self.compute_seconds_sum = Gauge(
            f"{ns}_compute_duration_seconds_sum", "Total compute seconds",
            registry=self.registry)
        self.request_quantiles = Gauge(
            f"{ns}_request_duration_seconds", "Request duration quantiles",
            ["quantile"], registry=self.registry)
        self.compute_quantiles = Gauge(
            f"{ns}_compute_duration_seconds", "Compute duration quantiles",
            ["quantile"], registry=self.registry)
        self.load_ratio = Histogram(
            f"{ns}_load_ratio", "request/compute duration ratio",
            buckets=LOAD_RATIO_BUCKETS, registry=self.registry)
        self.hbm_bytes_in_use = Gauge(
            f"{ns}_hbm_bytes_in_use", "Device HBM in use (power-gauge analog)",
            registry=self.registry)
        self.framework_hbm_bytes = Gauge(
            f"{ns}_framework_hbm_bytes",
            "HBM owned via the device allocator framework (weights, KV "
            "page stores) — the size_tracker figure",
            registry=self.registry)
        self.queue_depth = Gauge(
            f"{ns}_queue_depth", "In-flight requests (NVRPC_METRICS hook)",
            registry=self.registry)

    # -- observation hooks ---------------------------------------------------
    _REFRESH_EVERY = 64  # quantile refresh cadence (full reservoir sort)

    def observe_request(self, request_s: float, compute_s: float) -> None:
        self.request_count.inc()
        self.request_seconds_sum.inc(request_s)
        self.compute_seconds_sum.inc(compute_s)
        self._request.observe(request_s)
        self._compute.observe(compute_s)
        if compute_s > 0:
            self.load_ratio.observe(request_s / compute_s)
        # quantile gauges refresh periodically (and from the control lambda),
        # not per request — the sort is too heavy for the hot path
        self._since_refresh = getattr(self, "_since_refresh", 0) + 1
        if self._since_refresh == 1 or self._since_refresh >= self._REFRESH_EVERY:
            self.refresh_quantiles()

    def refresh_quantiles(self) -> None:
        self._since_refresh = 0
        for q in _QUANTILES:
            self.request_quantiles.labels(quantile=str(q)).set(
                self._request.quantile(q))
            self.compute_quantiles.labels(quantile=str(q)).set(
                self._compute.quantile(q))

    def inc_queue_depth(self) -> None:
        self.queue_depth.inc()

    def dec_queue_depth(self) -> None:
        self.queue_depth.dec()

    def poll_device(self, device_index: int = 0) -> None:
        """Control-lambda hook (reference NVML power gauge in Server::Run)."""
        from tpulab.tpu.device_info import DeviceInfo
        info = DeviceInfo.memory_info(device_index)
        if info.bytes_in_use is not None:
            self.hbm_bytes_in_use.set(info.bytes_in_use)
        from tpulab.tpu.allocators import TpuRawAllocator
        self.framework_hbm_bytes.set(TpuRawAllocator.total_bytes_in_use())
        self.refresh_quantiles()  # scrape-freshness without hot-path sorts


class ReplicaSetMetrics:
    """Observability for client-side replica routing
    (:mod:`tpulab.rpc.replica`): per-replica traffic/inflight/liveness and
    the failover counter — the client-side view envoy's upstream stats
    give in deployment."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.requests = Counter(
            f"{ns}_replica_requests_total",
            "Requests completed per replica", ["replica"],
            registry=self.registry)
        self.failovers = Counter(
            f"{ns}_replica_failovers_total",
            "Requests re-routed off a failed replica",
            registry=self.registry)
        self.inflight = Gauge(
            f"{ns}_replica_inflight", "In-flight requests per replica",
            ["replica"], registry=self.registry)
        self.live = Gauge(
            f"{ns}_replica_live",
            "Last health-probe liveness per replica (1/0)", ["replica"],
            registry=self.registry)


class GenerationMetrics:
    """LLM-serving observability for a ContinuousBatcher: lane/queue/page
    gauges plus token/request/preemption/prefix-cache counters.  Sampled
    by ``poll(batcher)`` (cheap attribute reads; counters advance by the
    delta since the last poll, so rate() works in PromQL)."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.active_lanes = Gauge(
            f"{ns}_llm_active_lanes", "Decode lanes in use",
            registry=self.registry)
        self.queued = Gauge(
            f"{ns}_llm_queued_requests", "Requests waiting for a lane",
            registry=self.registry)
        self.free_pages = Gauge(
            f"{ns}_llm_free_pages", "KV pool pages free",
            registry=self.registry)
        self.tokens = Counter(
            f"{ns}_llm_tokens", "Tokens generated",
            registry=self.registry)
        self.completed = Counter(
            f"{ns}_llm_requests_completed", "Generation requests completed",
            registry=self.registry)
        self.preemptions = Counter(
            f"{ns}_llm_preemptions", "Priority preemptions",
            registry=self.registry)
        self.prefix_hits = Counter(
            f"{ns}_llm_prefix_cache_hits", "Prefix-cache page hits",
            registry=self.registry)
        self.prefix_misses = Counter(
            f"{ns}_llm_prefix_cache_misses", "Prefix pages computed fresh",
            registry=self.registry)
        self._last: Dict[str, int] = {}

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, batcher) -> None:
        """Sample a ContinuousBatcher (control-loop / poller hook)."""
        self.active_lanes.set(batcher.active_lanes)
        self.queued.set(batcher.queued_requests)
        try:
            self.free_pages.set(batcher.pool.free_pages)
        except Exception:  # pragma: no cover - closed pool during teardown
            pass
        self._advance(self.tokens, "tokens", batcher.tokens_generated)
        self._advance(self.completed, "completed",
                      batcher.completed_requests)
        self._advance(self.preemptions, "preempt", batcher.preemptions)
        pc = getattr(batcher, "prefix_cache", None)
        if pc is not None:
            self._advance(self.prefix_hits, "hits", pc.hits)
            self._advance(self.prefix_misses, "misses", pc.misses)


def start_metrics_server(metrics, port: int = 9090):
    """Expose /metrics (reference Exposer on :8080).  Accepts any metrics
    holder with a ``registry`` attribute (InferenceMetrics,
    ReplicaSetMetrics, ...)."""
    return start_http_server(port, registry=metrics.registry)
