"""Prometheus metrics (reference examples/02 metrics.h/cc:27-107 — singleton
Exposer+Registry; compute/request duration summaries with p50/p90/p99
quantiles; load-ratio histogram {1.25,1.5,2,10,100}; device power gauge
polled from Server::Run's control lambda).

prometheus_client has no quantile Summary, so the duration summaries are
implemented the way the reference's consumers read them: sliding-window
reservoirs exported as per-quantile gauges, next to total count/sum counters.
The NVML power gauge's TPU analog is the HBM usage gauge (polled from the
server control lambda via :meth:`InferenceMetrics.poll_device`).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as np

try:
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, start_http_server)
    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

#: reference load-ratio buckets (metrics.cc): request_time / compute_time
LOAD_RATIO_BUCKETS = (1.25, 1.5, 2.0, 10.0, 100.0)

_QUANTILES = (0.5, 0.9, 0.99)

#: latency-distribution buckets (seconds).  TTFT/queue cover the serving
#: SLO range (1 ms .. 10 s); ITL is finer (decode ticks are sub-10ms on
#: chip); e2e stretches to streaming-request lifetimes.
TTFT_BUCKETS = (.001, .0025, .005, .01, .025, .05, .1, .25, .5, 1., 2.5,
                5., 10.)
ITL_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5, 1.)
E2E_BUCKETS = (.01, .025, .05, .1, .25, .5, 1., 2.5, 5., 10., 30., 60.)
#: deadline slack-at-completion: how close completed requests run to their
#: budget (small slack = the deadline is doing work; see OBSERVABILITY.md)
SLACK_BUCKETS = (.001, .005, .01, .025, .05, .1, .25, .5, 1., 2.5, 5.,
                 10., 30.)

#: circuit-breaker states exported per replica (rpc/replica.py)
BREAKER_STATES = ("closed", "open", "probing")


class _Reservoir:
    """Sliding-window quantile reservoir backing a 'summary'."""

    def __init__(self, size: int = 2048):
        self._buf = np.zeros(size, np.float64)
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = value
            self._n += 1

    def quantile(self, q: float) -> float:
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return 0.0
            return float(np.percentile(self._buf[:n], q * 100))


class InferenceMetrics:
    """The example-02 metric set for one service."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self._request = _Reservoir()
        self._compute = _Reservoir()
        self.request_count = Counter(
            f"{ns}_request_total", "Requests completed", registry=self.registry)
        # Gauges (not Counters) so the exported sample keeps the summary
        # convention `..._seconds_sum` — Counter would append `_total`.
        self.request_seconds_sum = Gauge(
            f"{ns}_request_duration_seconds_sum", "Total request seconds",
            registry=self.registry)
        self.compute_seconds_sum = Gauge(
            f"{ns}_compute_duration_seconds_sum", "Total compute seconds",
            registry=self.registry)
        self.request_quantiles = Gauge(
            f"{ns}_request_duration_seconds", "Request duration quantiles",
            ["quantile"], registry=self.registry)
        self.compute_quantiles = Gauge(
            f"{ns}_compute_duration_seconds", "Compute duration quantiles",
            ["quantile"], registry=self.registry)
        self.load_ratio = Histogram(
            f"{ns}_load_ratio", "request/compute duration ratio",
            buckets=LOAD_RATIO_BUCKETS, registry=self.registry)
        self.hbm_bytes_in_use = Gauge(
            f"{ns}_hbm_bytes_in_use", "Device HBM in use (power-gauge analog)",
            registry=self.registry)
        self.framework_hbm_bytes = Gauge(
            f"{ns}_framework_hbm_bytes",
            "HBM owned via the device allocator framework (weights, KV "
            "page stores) — the size_tracker figure",
            registry=self.registry)
        self.queue_depth = Gauge(
            f"{ns}_queue_depth", "In-flight requests (NVRPC_METRICS hook)",
            registry=self.registry)
        # -- per-model dimension (multi-model serving) ----------------------
        self.model_requests = Counter(
            f"{ns}_requests_by_model", "Requests completed, per model",
            ["model"], registry=self.registry)
        self.model_request_seconds = Histogram(
            f"{ns}_request_duration_seconds_by_model",
            "Request latency distribution, per model",
            ["model"], buckets=E2E_BUCKETS, registry=self.registry)
        # quantile refresh cadence state: counter + lock live here (not
        # lazily in observe_request) so two racing observers cannot both
        # read a stale count and both skip the refresh
        self._since_refresh = 0
        self._ever_refreshed = False
        self._refresh_lock = threading.Lock()

    # -- observation hooks ---------------------------------------------------
    _REFRESH_EVERY = 64  # quantile refresh cadence (full reservoir sort)

    def observe_request(self, request_s: float, compute_s: float,
                        model: Optional[str] = None) -> None:
        self.request_count.inc()
        if model:
            self.model_requests.labels(model=model).inc()
            self.model_request_seconds.labels(model=model).observe(
                max(0.0, request_s))
        self.request_seconds_sum.inc(request_s)
        self.compute_seconds_sum.inc(compute_s)
        self._request.observe(request_s)
        self._compute.observe(compute_s)
        if compute_s > 0:
            self.load_ratio.observe(request_s / compute_s)
        # quantile gauges refresh periodically (and from the control lambda),
        # not per request — the sort is too heavy for the hot path.  The
        # count-and-decide is atomic under the lock, so exactly one of N
        # racing observers crosses the threshold and pays the sort (the
        # pre-fix getattr dance let two skip it — or double-sort).  The
        # very first observation refreshes immediately (scrape freshness);
        # after that the cadence is every ``_REFRESH_EVERY``.
        with self._refresh_lock:
            self._since_refresh += 1
            do_refresh = (not self._ever_refreshed
                          or self._since_refresh >= self._REFRESH_EVERY)
        if do_refresh:
            self.refresh_quantiles()

    def refresh_quantiles(self) -> None:
        with self._refresh_lock:
            self._since_refresh = 0
            self._ever_refreshed = True
        for q in _QUANTILES:
            self.request_quantiles.labels(quantile=str(q)).set(
                self._request.quantile(q))
            self.compute_quantiles.labels(quantile=str(q)).set(
                self._compute.quantile(q))

    def inc_queue_depth(self) -> None:
        self.queue_depth.inc()

    def dec_queue_depth(self) -> None:
        self.queue_depth.dec()

    def poll_device(self, device_index: int = 0) -> None:
        """Control-lambda hook (reference NVML power gauge in Server::Run)."""
        from tpulab.tpu.device_info import DeviceInfo
        info = DeviceInfo.memory_info(device_index)
        if info.bytes_in_use is not None:
            self.hbm_bytes_in_use.set(info.bytes_in_use)
        from tpulab.tpu.allocators import TpuRawAllocator
        self.framework_hbm_bytes.set(TpuRawAllocator.total_bytes_in_use())
        self.refresh_quantiles()  # scrape-freshness without hot-path sorts


class ReplicaSetMetrics:
    """Observability for client-side replica routing
    (:mod:`tpulab.rpc.replica`): per-replica traffic/inflight/liveness,
    the failover counter, circuit-breaker state/transitions, per-attempt
    status-code counters and end-to-end deadline outcomes — the
    client-side view envoy's upstream stats give in deployment, plus the
    resilience telemetry the adaptive-orchestration line in PAPERS.md
    argues breakers/deadlines need in order to be tunable."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.requests = Counter(
            f"{ns}_replica_requests_total",
            "Requests completed per replica", ["replica"],
            registry=self.registry)
        self.failovers = Counter(
            f"{ns}_replica_failovers_total",
            "Requests re-routed off a failed replica",
            registry=self.registry)
        self.inflight = Gauge(
            f"{ns}_replica_inflight", "In-flight requests per replica",
            ["replica"], registry=self.registry)
        self.live = Gauge(
            f"{ns}_replica_live",
            "Last health-probe liveness per replica (1/0)", ["replica"],
            registry=self.registry)
        # -- circuit breaker (one-hot state + transition counters) ----------
        self.breaker_state = Gauge(
            f"{ns}_replica_breaker_state",
            "Circuit-breaker state per replica (one-hot over "
            "closed/open/probing)", ["replica", "state"],
            registry=self.registry)
        self.breaker_transitions = Counter(
            f"{ns}_replica_breaker_transitions_total",
            "Breaker transitions per replica, keyed by target state",
            ["replica", "to"], registry=self.registry)
        # -- per-attempt outcomes (retry/failover tuning input) -------------
        self.attempts = Counter(
            f"{ns}_replica_attempts_total",
            "Request attempts by terminal status code (OK, UNAVAILABLE, "
            "DEADLINE_EXCEEDED, INVALID_ARGUMENT, ...)", ["code"],
            registry=self.registry)
        # -- end-to-end deadline outcomes -----------------------------------
        self.deadline_outcomes = Counter(
            f"{ns}_deadline_outcomes_total",
            "Deadline-bounded requests by outcome (met/exceeded)",
            ["outcome"], registry=self.registry)
        self.deadline_slack = Histogram(
            f"{ns}_deadline_slack_seconds",
            "Remaining budget at completion of deadline-bounded requests",
            buckets=SLACK_BUCKETS, registry=self.registry)
        # -- durable streams (docs/ROBUSTNESS.md "Stream failover
        # semantics"): how much failover work was wasted vs resumed ------
        self.stalls = Counter(
            f"{ns}_replica_stream_stalls_total",
            "Streams failed over by the stall watchdog (no first token "
            "within the TTFT bound / no progress within the inter-token "
            "bound) — distinct from transport faults", registry=self.registry)
        self.resumes = Counter(
            f"{ns}_replica_stream_resumes_total",
            "Failover attempts resubmitted as resume-from-delivered "
            "(prompt+delivered re-prefilled, zero tokens replayed)",
            registry=self.registry)
        self.resume_fallbacks = Counter(
            f"{ns}_replica_stream_resume_fallbacks_total",
            "Resume attempts the server rejected, degraded to full replay",
            registry=self.registry)
        self.tokens_replayed = Counter(
            f"{ns}_replica_tokens_replayed_total",
            "Already-delivered tokens re-received and discarded on "
            "full-replay failovers (the waste resume removes)",
            registry=self.registry)
        self.hedges = Counter(
            f"{ns}_replica_hedges_total",
            "Duplicate first-token attempts launched after the hedge delay",
            registry=self.registry)
        self.hedge_wins = Counter(
            f"{ns}_replica_hedge_wins_total",
            "Hedged requests whose duplicate attempt delivered the first "
            "token (the primary lost the race)", registry=self.registry)
        # -- per-replica prefix-cache effectiveness (poll_load refreshes
        # from StatusResponse.prefix_hits/prefix_lookups) — the fleet view
        # prefix-affinity routing (ROADMAP item 1) needs: a returning
        # user landing on a random replica shows up here as hit rates
        # collapsing as the fleet widens ------------------------------------
        self.prefix_hits = Gauge(
            f"{ns}_replica_prefix_hits",
            "Server-reported prefix-cache pages served from cache, "
            "per replica (lifetime counter sampled as a gauge)",
            ["replica"], registry=self.registry)
        self.prefix_lookups = Gauge(
            f"{ns}_replica_prefix_lookups",
            "Server-reported prefix-cache pages looked up (hits + "
            "misses), per replica", ["replica"], registry=self.registry)
        # -- prefix-affinity routing (tpulab.fleet.router): did requests
        # land on their rendezvous home, and how much cache warmth do
        # membership changes cost ----------------------------------------
        self.affinity_hits = Counter(
            f"{ns}_replica_affinity_hits_total",
            "Requests routed to their prefix-affinity winner (rank 0 of "
            "the rendezvous ring)", registry=self.registry)
        self.affinity_spills = Counter(
            f"{ns}_replica_affinity_spills_total",
            "Requests whose affinity winner was skipped for load (queue "
            "depth / inflight / free-HBM spill thresholds) — the "
            "hot-prefix-never-a-hot-spot contract, counted",
            registry=self.registry)
        self.ring_moves = Counter(
            f"{ns}_replica_ring_moves_total",
            "Sampled prefix digests re-homed by ring membership changes "
            "(breaker ejections, drains, scale up/down) — rendezvous "
            "hashing keeps this near sampled/N per change",
            registry=self.registry)

    # -- hooks (called by the replica sets; cold paths) ---------------------
    def set_breaker_state(self, replica: str, state: str) -> None:
        """One-hot the per-replica state gauge (PromQL reads
        ``..._breaker_state{state="open"} == 1``)."""
        for s in BREAKER_STATES:
            self.breaker_state.labels(replica=replica, state=s).set(
                1 if s == state else 0)

    def note_breaker_transition(self, replica: str, to_state: str) -> None:
        self.breaker_transitions.labels(replica=replica, to=to_state).inc()
        self.set_breaker_state(replica, to_state)

    def note_attempt(self, code: str) -> None:
        self.attempts.labels(code=code).inc()

    def observe_deadline(self, met: bool,
                         slack_s: Optional[float] = None) -> None:
        self.deadline_outcomes.labels(
            outcome="met" if met else "exceeded").inc()
        if met and slack_s is not None:
            self.deadline_slack.observe(max(0.0, slack_s))

    # -- durable-stream hooks -------------------------------------------
    def note_stall(self) -> None:
        self.stalls.inc()

    def note_resume(self) -> None:
        self.resumes.inc()

    def note_resume_fallback(self) -> None:
        self.resume_fallbacks.inc()

    def note_tokens_replayed(self, n: int = 1) -> None:
        if n > 0:
            self.tokens_replayed.inc(n)

    def note_hedge(self, won: bool = False) -> None:
        if won:
            self.hedge_wins.inc()
        else:
            self.hedges.inc()

    # -- prefix-affinity hooks (tpulab.fleet.router) --------------------
    def note_affinity(self, hit: bool) -> None:
        if hit:
            self.affinity_hits.inc()
        else:
            self.affinity_spills.inc()

    def note_ring_moves(self, n: int = 1) -> None:
        if n > 0:
            self.ring_moves.inc(n)


class FleetMetrics:
    """Observability for the fleet control plane
    (:mod:`tpulab.fleet`): autoscaler membership actions and the
    queue-wait signal it scales on, plus the self-healing/election
    telemetry (:mod:`tpulab.fleet.supervisor` /
    :mod:`tpulab.fleet.election`) — replica deaths and respawns, the
    crash-loop breaker alert, and which node currently leads.  The
    elasticity telemetry the adaptive-orchestration line in PAPERS.md
    argues a scale controller needs in order to be tunable (is it
    flapping? is the wait threshold doing work? is a slot burning spawn
    budget?)."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.scale_ups = Counter(
            f"{ns}_fleet_scale_ups_total",
            "Replicas added by the autoscaler", registry=self.registry)
        self.scale_downs = Counter(
            f"{ns}_fleet_scale_downs_total",
            "Replicas retired by the autoscaler (drain completed)",
            registry=self.registry)
        self.drains = Counter(
            f"{ns}_fleet_drains_total",
            "Scale-down drains started (victim flagged draining; retired "
            "only once in-flight work completes)", registry=self.registry)
        self.replicas = Gauge(
            f"{ns}_fleet_replicas",
            "Active (routable, non-draining) replicas in the set",
            registry=self.registry)
        self.queue_wait = Gauge(
            f"{ns}_fleet_queue_wait_ewma_seconds",
            "The admission queue-wait EWMA the controller last evaluated "
            "(AdmissionController.queue_wait_ewma_s)",
            registry=self.registry)
        self.replica_deaths = Counter(
            f"{ns}_fleet_replica_deaths_total",
            "Replicas the supervisor declared dead (process exited or "
            "unreachable past the probe streak) — drains never count",
            registry=self.registry)
        self.respawns = Counter(
            f"{ns}_fleet_respawns_total",
            "Crashed replicas respawned by the supervisor (after "
            "exponential backoff)", registry=self.registry)
        self.crash_loops = Counter(
            f"{ns}_fleet_crash_loops_total",
            "Crash-loop breaker openings: a lineage died N times in the "
            "window and is quarantined (spawn budget stops burning; "
            "THIS is the alert to page on)", registry=self.registry)
        self.leader_transitions = Counter(
            f"{ns}_fleet_leader_transitions_total",
            "Times THIS node gained control-plane leadership (lease "
            "acquisitions; fleet-wide churn = sum over nodes)",
            registry=self.registry)
        self.is_leader = Gauge(
            f"{ns}_fleet_is_leader",
            "1 while this node holds the control-plane lease (runs the "
            "supervisor + autoscaler), else 0", registry=self.registry)
        self._was_leader = False

    # -- hooks (called by the control plane; cold paths) ----------------
    def note_scale(self, up: bool) -> None:
        (self.scale_ups if up else self.scale_downs).inc()

    def note_drain(self) -> None:
        self.drains.inc()

    def set_replicas(self, n: int) -> None:
        self.replicas.set(n)

    def set_queue_wait(self, seconds: float) -> None:
        self.queue_wait.set(max(0.0, float(seconds)))

    def note_death(self) -> None:
        self.replica_deaths.inc()

    def note_respawn(self) -> None:
        self.respawns.inc()

    def note_crash_loop(self) -> None:
        self.crash_loops.inc()

    def set_leader(self, leading: bool) -> None:
        """Gauge + edge-triggered transition counter (gains only)."""
        leading = bool(leading)
        self.is_leader.set(1 if leading else 0)
        if leading and not self._was_leader:
            self.leader_transitions.inc()
        self._was_leader = leading


class BatchMetrics:
    """Offline-batch-lane telemetry (`_batch_*`; tpulab.batch,
    docs/SERVING.md "Offline batch lane"): job/item progress, how often
    online traffic evicted the lane, tokens delivered vs re-decode the
    checkpoint resume avoided, and the utilization-soak gauge — is the
    lane actually converting idle capacity into tokens.  Sampled by
    ``poll(scheduler)`` (cheap attribute reads; counters advance by the
    delta since the last poll, so rate() works in PromQL)."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.jobs_running = Gauge(
            f"{ns}_batch_jobs_running",
            "Batch jobs a scheduler is currently running",
            registry=self.registry)
        self.jobs_done = Counter(
            f"{ns}_batch_jobs_done_total",
            "Batch jobs run to completion (every item done)",
            registry=self.registry)
        self.jobs_interrupted = Counter(
            f"{ns}_batch_jobs_interrupted_total",
            "Batch runs killed mid-job (chaos/timeout); the next run "
            "resumes from the JSONL checkpoint", registry=self.registry)
        self.items_done = Counter(
            f"{ns}_batch_items_done_total",
            "Job items (prompts) completed", registry=self.registry)
        self.preemptions = Counter(
            f"{ns}_batch_preemptions_total",
            "Batch-class lanes evicted by online arrivals (the lane is "
            "the FIRST preemption victim by design — a high count with "
            "healthy online latencies is the lane working)",
            registry=self.registry)
        self.tokens_delivered = Counter(
            f"{ns}_batch_tokens_delivered_total",
            "Tokens delivered to batch result sinks",
            registry=self.registry)
        self.tokens_replay_avoided = Counter(
            f"{ns}_batch_tokens_replay_avoided_total",
            "Delivered tokens a checkpoint resume did NOT re-decode "
            "(the prompt+delivered prefix rode one chunked prefill)",
            registry=self.registry)
        self.spare_denials = Counter(
            f"{ns}_batch_spare_denials_total",
            "Feed attempts deferred by the spare-capacity gate (idle "
            "lanes / unified headroom / arbiter floor)",
            registry=self.registry)
        self.soak_utilization = Gauge(
            f"{ns}_batch_soak_utilization",
            "Fraction of engine lanes the batch lane occupies right now "
            "(near 1 on an idle fleet, near 0 under online load — both "
            "are the lane working as designed)", registry=self.registry)
        self._last: Dict[str, int] = {}

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, scheduler) -> None:
        """Sample a tpulab.batch.BatchScheduler (control-loop hook)."""
        self.jobs_running.set(getattr(scheduler, "jobs_running", 0))
        self.soak_utilization.set(
            getattr(scheduler, "soak_utilization", 0.0))
        self._advance(self.jobs_done, "jobs_done",
                      getattr(scheduler, "jobs_done", 0))
        self._advance(self.jobs_interrupted, "interrupted",
                      getattr(scheduler, "interrupted_runs", 0))
        self._advance(self.items_done, "items_done",
                      getattr(scheduler, "items_done", 0))
        self._advance(self.tokens_delivered, "tokens",
                      getattr(scheduler, "tokens_delivered", 0))
        self._advance(self.tokens_replay_avoided, "replay_avoided",
                      getattr(scheduler, "tokens_resume_skipped", 0))
        self._advance(self.spare_denials, "spare_denials",
                      getattr(scheduler, "spare_denials", 0))
        eng = getattr(scheduler, "engine", None)
        if eng is not None:
            self._advance(self.preemptions, "preemptions",
                          getattr(eng, "batch_preemptions", 0))


class GenerationMetrics:
    """LLM-serving observability for a ContinuousBatcher: lane/queue/page
    gauges plus token/request/preemption/prefix-cache counters.  Sampled
    by ``poll(batcher)`` (cheap attribute reads; counters advance by the
    delta since the last poll, so rate() works in PromQL).

    Latency DISTRIBUTIONS (TTFT, inter-token latency, queue wait, end to
    end) are event-driven, not polled: pass this object as the batcher's
    ``metrics=`` and it observes every completed request at the source —
    the distinction the inference-frameworks-benchmark line in PAPERS.md
    shows actually separates serving stacks (means hide the tail).
    ``ttft_quantiles()`` / ``itl_quantiles()`` feed bench.py's tail-latency
    rows from sliding-window reservoirs (exact quantiles, not bucket
    interpolation)."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None,
                 model: str = ""):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        #: model name tagging this engine's per-model samples (multi-model
        #: serving: one GenerationMetrics per engine; "" = untagged)
        self.model_label = model
        self.active_lanes = Gauge(
            f"{ns}_llm_active_lanes", "Decode lanes in use",
            registry=self.registry)
        self.queued = Gauge(
            f"{ns}_llm_queued_requests", "Requests waiting for a lane",
            registry=self.registry)
        self.free_pages = Gauge(
            f"{ns}_llm_free_pages", "KV pool pages free",
            registry=self.registry)
        self.tokens = Counter(
            f"{ns}_llm_tokens", "Tokens generated",
            registry=self.registry)
        self.completed = Counter(
            f"{ns}_llm_requests_completed", "Generation requests completed",
            registry=self.registry)
        self.preemptions = Counter(
            f"{ns}_llm_preemptions", "Priority preemptions",
            registry=self.registry)
        self.prefix_hits = Counter(
            f"{ns}_llm_prefix_cache_hits", "Prefix-cache page hits",
            registry=self.registry)
        self.prefix_misses = Counter(
            f"{ns}_llm_prefix_cache_misses", "Prefix pages computed fresh",
            registry=self.registry)
        # -- latency distributions (observed per request by the batcher) ----
        self.ttft = Histogram(
            f"{ns}_llm_ttft_seconds",
            "Time to first token (submit -> first emitted token)",
            buckets=TTFT_BUCKETS, registry=self.registry)
        self.itl = Histogram(
            f"{ns}_llm_inter_token_seconds",
            "Inter-token latency (per decoded token after the first)",
            buckets=ITL_BUCKETS, registry=self.registry)
        self.queue_wait = Histogram(
            f"{ns}_llm_queue_wait_seconds",
            "Submit -> prefill start (lane + page admission wait)",
            buckets=TTFT_BUCKETS, registry=self.registry)
        self.e2e = Histogram(
            f"{ns}_llm_e2e_seconds",
            "Submit -> last token (completed requests)",
            buckets=E2E_BUCKETS, registry=self.registry)
        self.deadline_expired = Counter(
            f"{ns}_llm_deadline_expired_total",
            "Requests the batcher cancelled at deadline expiry",
            registry=self.registry)
        # -- fused-decode dispatch efficiency (multi-step decode blocks) ----
        self.decode_dispatches = Counter(
            f"{ns}_llm_decode_dispatches",
            "Fused decode dispatches (K-token blocks and single ticks)",
            registry=self.registry)
        self.decode_host_syncs = Counter(
            f"{ns}_llm_decode_host_syncs",
            "Blocking device->host result fetches in decode",
            registry=self.registry)
        self.ragged_dispatches = Counter(
            f"{ns}_llm_ragged_dispatches",
            "Dispatches through the ragged paged-attention family "
            "(mixed prefill+decode rounds, plus decode/verify dispatches "
            "whose attention ran the pallas ragged kernel)",
            registry=self.registry)
        self.dispatches_by_kind = Counter(
            f"{ns}_llm_dispatches_by_kind",
            "Decode dispatches by ragged-plan dispatch kind "
            "(decode = K-blocks/single ticks, verify = speculative "
            "draft+verify blocks, mixed = ragged prefill+decode rounds)",
            ["kind"], registry=self.registry)
        self.tokens_per_dispatch = Gauge(
            f"{ns}_llm_tokens_per_dispatch",
            "Generated tokens per decode dispatch (lifetime ratio; ~K x "
            "lanes when fused blocks run full)", registry=self.registry)
        self.host_syncs_per_token = Gauge(
            f"{ns}_llm_host_syncs_per_token",
            "Blocking host syncs per generated token (1.0 = per-token "
            "round trips; ~1/(K*lanes) under fused decode)",
            registry=self.registry)
        # -- speculative decode (draft/verify blocks; engine/paged.py) ------
        self.spec_tokens_drafted = Counter(
            f"{ns}_llm_spec_tokens_drafted",
            "Draft-model proposals verified by the target (accepted or "
            "rejected)", registry=self.registry)
        self.spec_tokens_accepted = Counter(
            f"{ns}_llm_spec_tokens_accepted",
            "Draft proposals the target accepted (emitted as output "
            "tokens)", registry=self.registry)
        self.spec_fallbacks = Counter(
            f"{ns}_llm_spec_fallbacks",
            "Lanes degraded from speculative to plain decode blocks "
            "(low acceptance, chaos verify trips)",
            registry=self.registry)
        self.spec_probes = Counter(
            f"{ns}_llm_spec_probes",
            "Probe blocks re-trying speculation on a transiently degraded "
            "lane (acceptance-EWMA degrades only)", registry=self.registry)
        self.spec_probe_recoveries = Counter(
            f"{ns}_llm_spec_probe_recoveries",
            "Probe blocks whose lane recovered to speculative decode "
            "(acceptance back above the floor)", registry=self.registry)
        self.spec_acceptance_rate = Gauge(
            f"{ns}_llm_spec_acceptance_rate",
            "Lifetime draft acceptance rate (accepted / drafted) — the "
            "multiplier on the decode-block dispatch amortization",
            registry=self.registry)
        # -- durable streams: server-side resume admissions -----------------
        self.resumed_streams = Counter(
            f"{ns}_llm_resumed_streams",
            "Generate streams admitted as resume-from-delivered "
            "(prompt+delivered through one chunked prefill)",
            registry=self.registry)
        self.tokens_resume_skipped = Counter(
            f"{ns}_llm_tokens_resume_skipped",
            "Already-delivered tokens a resume admission did NOT re-decode "
            "(each rode the prefill instead of a sequential decode step)",
            registry=self.registry)
        # -- per-model dimension (multi-model serving) ----------------------
        self.model_tokens = Counter(
            f"{ns}_llm_tokens_by_model", "Tokens generated, per model",
            ["model"], registry=self.registry)
        self.model_completed = Counter(
            f"{ns}_llm_requests_completed_by_model",
            "Generation requests completed, per model",
            ["model"], registry=self.registry)
        self.model_ttft = Histogram(
            f"{ns}_llm_ttft_seconds_by_model",
            "Time to first token, per model",
            ["model"], buckets=TTFT_BUCKETS, registry=self.registry)
        self.model_itl = Histogram(
            f"{ns}_llm_inter_token_seconds_by_model",
            "Inter-token latency, per model",
            ["model"], buckets=ITL_BUCKETS, registry=self.registry)
        self._ttft_res = _Reservoir()
        self._itl_res = _Reservoir()
        self._last: Dict[str, int] = {}

    # -- event hooks (called by the batcher; see engine/paged.py) -----------
    def observe_queue_wait(self, seconds: float) -> None:
        self.queue_wait.observe(max(0.0, seconds))

    def observe_ttft(self, seconds: float) -> None:
        seconds = max(0.0, seconds)
        self.ttft.observe(seconds)
        if self.model_label:
            self.model_ttft.labels(model=self.model_label).observe(seconds)
        self._ttft_res.observe(seconds)

    def observe_itl(self, seconds: float) -> None:
        seconds = max(0.0, seconds)
        self.itl.observe(seconds)
        if self.model_label:
            self.model_itl.labels(model=self.model_label).observe(seconds)
        self._itl_res.observe(seconds)

    def observe_e2e(self, seconds: float) -> None:
        self.e2e.observe(max(0.0, seconds))

    def note_deadline_expired(self) -> None:
        self.deadline_expired.inc()

    def note_resume(self, tokens_skipped: int) -> None:
        """One resume-from-delivered admission (Generate RPC): the
        delivered prefix rode the prefill instead of re-decoding."""
        self.resumed_streams.inc()
        if tokens_skipped > 0:
            self.tokens_resume_skipped.inc(tokens_skipped)

    def ttft_quantiles(self) -> Dict[str, float]:
        return {f"p{int(q * 100)}": self._ttft_res.quantile(q)
                for q in _QUANTILES}

    def itl_quantiles(self) -> Dict[str, float]:
        return {f"p{int(q * 100)}": self._itl_res.quantile(q)
                for q in _QUANTILES}

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, batcher) -> None:
        """Sample a ContinuousBatcher (control-loop / poller hook)."""
        self.active_lanes.set(batcher.active_lanes)
        self.queued.set(batcher.queued_requests)
        try:
            self.free_pages.set(batcher.pool.free_pages)
        except AttributeError:  # closed/absent pool during teardown (a
            pass                # wrapped engine without .pool, or a pool
            #                     whose accounting died with close()) — any
            #                     other failure is a real bug and raises
        self._advance(self.tokens, "tokens", batcher.tokens_generated)
        self._advance(self.completed, "completed",
                      batcher.completed_requests)
        if self.model_label:
            self._advance(self.model_tokens.labels(model=self.model_label),
                          "model_tokens", batcher.tokens_generated)
            self._advance(
                self.model_completed.labels(model=self.model_label),
                "model_completed", batcher.completed_requests)
        self._advance(self.preemptions, "preempt", batcher.preemptions)
        # fused-decode dispatch efficiency (getattr: wrapped engines may
        # not expose the counters)
        dispatches = getattr(batcher, "decode_dispatches", 0)
        syncs = getattr(batcher, "decode_host_syncs", 0)
        self._advance(self.decode_dispatches, "dispatches", dispatches)
        self._advance(self.decode_host_syncs, "syncs", syncs)
        self._advance(self.ragged_dispatches, "ragged",
                      getattr(batcher, "ragged_dispatches", 0))
        for kind, n in getattr(batcher, "dispatch_kinds", {}).items():
            self._advance(self.dispatches_by_kind.labels(kind=kind),
                          f"kind_{kind}", n)
        # speculative decode telemetry: tokens_generated counts EMITTED
        # (accepted) tokens only, so tokens_per_dispatch below is never
        # inflated by drafted-but-rejected proposals — those show up
        # exclusively in the drafted/accepted pair and the rate gauge
        drafted = getattr(batcher, "spec_tokens_drafted", 0)
        accepted = getattr(batcher, "spec_tokens_accepted", 0)
        self._advance(self.spec_tokens_drafted, "spec_drafted", drafted)
        self._advance(self.spec_tokens_accepted, "spec_accepted", accepted)
        self._advance(self.spec_fallbacks, "spec_fallbacks",
                      getattr(batcher, "spec_fallbacks", 0))
        self._advance(self.spec_probes, "spec_probes",
                      getattr(batcher, "spec_probes", 0))
        self._advance(self.spec_probe_recoveries, "spec_probe_recoveries",
                      getattr(batcher, "spec_probe_recoveries", 0))
        if drafted:
            self.spec_acceptance_rate.set(accepted / drafted)
        if dispatches:
            self.tokens_per_dispatch.set(
                batcher.tokens_generated / dispatches)
        if batcher.tokens_generated:
            self.host_syncs_per_token.set(
                syncs / batcher.tokens_generated)
        pc = getattr(batcher, "prefix_cache", None)
        if pc is not None:
            self._advance(self.prefix_hits, "hits", pc.hits)
            self._advance(self.prefix_misses, "misses", pc.misses)


#: swap latency buckets (seconds): device<->host page copies — sub-ms on
#: direct-attached hosts through tens of ms on relayed PjRt links
SWAP_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                1., 2.5)


class KVTierMetrics:
    """Tiered-KV-cache telemetry (`_kv_tier_*`; tpulab.kvcache): swap
    in/out bytes and latency distributions, demotion/promotion/drop
    counters, recompute-tokens-saved, and host-tier occupancy gauges —
    the view that says whether HBM pressure is being absorbed by the
    host tier (demotions + promotions + tokens saved) or still destroying
    state (drops + swap failures).  Latency/bytes are event-driven (pass
    this object as the manager's ``metrics=``); counters/gauges advance
    via :meth:`poll`."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.swap_out_bytes = Counter(
            f"{ns}_kv_tier_swap_out_bytes",
            "KV bytes copied device->host (lane swaps + demotions)",
            registry=self.registry)
        self.swap_in_bytes = Counter(
            f"{ns}_kv_tier_swap_in_bytes",
            "KV bytes copied host->device (restores + promotions)",
            registry=self.registry)
        self.swap_out_seconds = Histogram(
            f"{ns}_kv_tier_swap_out_seconds",
            "Swap-out latency (gather dispatch -> host-tier resident; "
            "write-behind, so this is BEHIND the decode loop)",
            buckets=SWAP_BUCKETS, registry=self.registry)
        self.swap_in_seconds = Histogram(
            f"{ns}_kv_tier_swap_in_seconds",
            "Swap-in latency (restore entry -> scatter dispatched)",
            buckets=SWAP_BUCKETS, registry=self.registry)
        self.swap_outs = Counter(
            f"{ns}_kv_tier_swap_outs", "Preempted-lane KV snapshots taken",
            registry=self.registry)
        self.swap_ins = Counter(
            f"{ns}_kv_tier_swap_ins",
            "Recompute-free resumes (snapshot restored, no re-prefill)",
            registry=self.registry)
        self.demotions = Counter(
            f"{ns}_kv_tier_demotions",
            "Prefix-cache pages demoted to the host tier",
            registry=self.registry)
        self.promotions = Counter(
            f"{ns}_kv_tier_promotions",
            "Prefix-cache pages promoted back from the host tier",
            registry=self.registry)
        self.swap_failures = Counter(
            f"{ns}_kv_tier_swap_failures",
            "Swaps degraded to the recompute path (chaos, transfer "
            "errors)", registry=self.registry)
        self.swap_drops = Counter(
            f"{ns}_kv_tier_swap_drops",
            "Snapshots the host tier's budget refused (distinct from "
            "transfer failures: a sustained count means the host budget "
            "is undersized)", registry=self.registry)
        self.host_drops = Counter(
            f"{ns}_kv_tier_host_drops",
            "Payloads refused by the host tier (larger than the budget)",
            registry=self.registry)
        self.host_evictions = Counter(
            f"{ns}_kv_tier_host_evictions",
            "Host-tier LRU entries pushed out by budget pressure",
            registry=self.registry)
        self.recompute_tokens_saved = Counter(
            f"{ns}_kv_tier_recompute_tokens_saved",
            "Prefill tokens resumes did NOT recompute (the tier's work "
            "saved, in tokens)", registry=self.registry)
        self.host_bytes = Gauge(
            f"{ns}_kv_tier_host_bytes", "Host-tier payload bytes resident",
            registry=self.registry)
        self.host_entries = Gauge(
            f"{ns}_kv_tier_host_entries", "Host-tier entries resident",
            registry=self.registry)
        self._last: Dict[str, int] = {}

    # -- event hooks (called by KVOffloadManager) ----------------------------
    def observe_swap_out(self, seconds: float, nbytes: int) -> None:
        self.swap_out_seconds.observe(max(0.0, seconds))

    def observe_swap_in(self, seconds: float, nbytes: int) -> None:
        self.swap_in_seconds.observe(max(0.0, seconds))

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, manager) -> None:
        """Sample a KVOffloadManager (control-loop / poller hook)."""
        self._advance(self.swap_out_bytes, "ob", manager.swap_out_bytes)
        self._advance(self.swap_in_bytes, "ib", manager.swap_in_bytes)
        self._advance(self.swap_outs, "so", manager.swap_outs)
        self._advance(self.swap_ins, "si", manager.swap_ins)
        self._advance(self.demotions, "dem", manager.demotions)
        self._advance(self.promotions, "pro", manager.promotions)
        self._advance(self.swap_failures, "fail", manager.swap_failures)
        self._advance(self.swap_drops, "sdrop", manager.swap_drops)
        self._advance(self.recompute_tokens_saved, "saved",
                      manager.recompute_tokens_saved)
        store = manager.store
        self._advance(self.host_drops, "drops", store.drops)
        self._advance(self.host_evictions, "evict", store.evictions)
        self.host_bytes.set(store.bytes_used)
        self.host_entries.set(len(store))


class KVFabricMetrics:
    """Fleet KV fabric telemetry (`_kvfabric_*`; tpulab.kvfabric): pull
    counts/bytes and fetch-latency distribution, single-flight
    coalesces, cost-gate skips, degrades and recompute-tokens-saved —
    the view that says whether routed-astray requests are adopting the
    fleet's warmth (pulls + tokens saved) or still recomputing it
    (degrades), and whether the guard rails are earning their keep
    (coalesces under fetch storms, cost-gate skips when the wire is
    slower than the chip).  Latency/bytes are event-driven (pass this
    object as the fabric's ``metrics=``); counters advance via
    :meth:`poll`."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.pulls = Counter(
            f"{ns}_kvfabric_pulls",
            "Prefix-KV pulls fetched from a home replica and adopted "
            "locally (each replaced a whole local prefill)",
            registry=self.registry)
        self.pull_bytes = Counter(
            f"{ns}_kvfabric_pull_bytes",
            "Wire bytes fetched over FetchKV", registry=self.registry)
        self.pull_seconds = Histogram(
            f"{ns}_kvfabric_pull_seconds",
            "FetchKV fetch latency (RPC start -> snapshot decoded and "
            "geometry-validated)", buckets=SWAP_BUCKETS,
            registry=self.registry)
        self.coalesced = Counter(
            f"{ns}_kvfabric_coalesced",
            "Concurrent same-digest misses served by another thread's "
            "in-flight fetch (single-flight)", registry=self.registry)
        self.cost_gate_skips = Counter(
            f"{ns}_kvfabric_cost_gate_skips",
            "Pulls skipped because the fetch-time estimate exceeded the "
            "local recompute estimate", registry=self.registry)
        self.degrades = Counter(
            f"{ns}_kvfabric_degrades",
            "Pull attempts degraded to a local prefill (NOT_FOUND, "
            "chaos, transport, corrupt wire, budget refusal, admission "
            "rejection)", registry=self.registry)
        self.recompute_tokens_saved = Counter(
            f"{ns}_kvfabric_recompute_tokens_saved",
            "Prefill tokens pulls did NOT recompute (the fabric's work "
            "saved, in tokens)", registry=self.registry)
        self._last: Dict[str, int] = {}

    # -- event hooks (called by KVFabric) ------------------------------------
    def observe_pull(self, seconds: float, nbytes: int) -> None:
        self.pull_seconds.observe(max(0.0, seconds))

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, fabric) -> None:
        """Sample a KVFabric (control-loop / poller hook)."""
        self._advance(self.pulls, "p", fabric.pulls)
        self._advance(self.pull_bytes, "pb", fabric.pull_bytes)
        self._advance(self.coalesced, "co", fabric.coalesced)
        self._advance(self.cost_gate_skips, "cg", fabric.cost_gate_skips)
        self._advance(self.degrades, "dg", fabric.degrades)
        self._advance(self.recompute_tokens_saved, "sv",
                      fabric.recompute_tokens_saved)


class ModelStoreMetrics:
    """Multi-model weight-tier telemetry (`_modelstore_*`;
    tpulab.modelstore): resident-vs-host-tier model gauges, weight swap
    in/out counters + latency distributions, evictions and cold rebuilds
    — the view that says whether the hot set is cycling cheaply
    (swap-ins, bounded latency) or thrashing back to cold rebuilds
    (failures + rebuilds).  Latency/bytes are event-driven (pass this
    object as the multiplexer's ``metrics=``); counters/gauges advance
    via :meth:`poll`."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.resident_models = Gauge(
            f"{ns}_modelstore_resident_models",
            "Models currently HBM-resident (hot)", registry=self.registry)
        self.host_tier_models = Gauge(
            f"{ns}_modelstore_host_tier_models",
            "Models parked in the host weight tier (cold)",
            registry=self.registry)
        self.hbm_bytes = Gauge(
            f"{ns}_modelstore_hbm_bytes",
            "Weight bytes accounted against the HBM budget (hot models "
            "plus unsettled swaps)", registry=self.registry)
        self.host_bytes = Gauge(
            f"{ns}_modelstore_host_bytes",
            "Host-tier weight bytes resident", registry=self.registry)
        self.swap_ins = Counter(
            f"{ns}_modelstore_swap_ins",
            "Models promoted host->device (bit-exact weight restores)",
            registry=self.registry)
        self.swap_outs = Counter(
            f"{ns}_modelstore_swap_outs",
            "Model weight snapshots landed device->host (write-behind)",
            registry=self.registry)
        self.swap_in_bytes = Counter(
            f"{ns}_modelstore_swap_in_bytes",
            "Weight bytes copied host->device", registry=self.registry)
        self.swap_out_bytes = Counter(
            f"{ns}_modelstore_swap_out_bytes",
            "Weight bytes copied device->host", registry=self.registry)
        self.swap_in_seconds = Histogram(
            f"{ns}_modelstore_swap_in_seconds",
            "Swap-in latency (host pop -> weights attached)",
            buckets=SWAP_BUCKETS, registry=self.registry)
        self.swap_out_seconds = Histogram(
            f"{ns}_modelstore_swap_out_seconds",
            "Swap-out latency (detach -> host-tier resident; write-"
            "behind, so this is BEHIND the request path)",
            buckets=SWAP_BUCKETS, registry=self.registry)
        self.evictions = Counter(
            f"{ns}_modelstore_evictions",
            "Models pushed out of HBM by budget pressure",
            registry=self.registry)
        self.cold_rebuilds = Counter(
            f"{ns}_modelstore_cold_rebuilds",
            "Acquires served by a fresh build (weights in no tier: "
            "degraded swaps, host-budget refusals)",
            registry=self.registry)
        self.swap_failures = Counter(
            f"{ns}_modelstore_swap_failures",
            "Weight swaps degraded to the cold-rebuild path (chaos, "
            "transfer errors)", registry=self.registry)
        self.swap_drops = Counter(
            f"{ns}_modelstore_swap_drops",
            "Weight snapshots the host tier's budget refused (sustained "
            "count = host budget undersized)", registry=self.registry)
        self.host_evictions = Counter(
            f"{ns}_modelstore_host_evictions",
            "Host-tier LRU models pushed out by budget pressure",
            registry=self.registry)
        self._last: Dict[str, int] = {}

    # -- event hooks (called by WeightMultiplexer) ---------------------------
    def observe_swap_in(self, seconds: float, nbytes: int) -> None:
        self.swap_in_seconds.observe(max(0.0, seconds))

    def observe_swap_out(self, seconds: float, nbytes: int) -> None:
        self.swap_out_seconds.observe(max(0.0, seconds))

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, mux) -> None:
        """Sample a WeightMultiplexer (control-loop / poller hook)."""
        self._advance(self.swap_ins, "si", mux.swap_ins)
        self._advance(self.swap_outs, "so", mux.swap_outs)
        self._advance(self.swap_in_bytes, "sib", mux.swap_in_bytes)
        self._advance(self.swap_out_bytes, "sob", mux.swap_out_bytes)
        self._advance(self.evictions, "ev", mux.evictions)
        self._advance(self.cold_rebuilds, "cr", mux.cold_rebuilds)
        self._advance(self.swap_failures, "sf", mux.swap_failures)
        self._advance(self.swap_drops, "sd", mux.swap_drops)
        self._advance(self.host_evictions, "he", mux.store.evictions)
        self.resident_models.set(len(mux.resident_models()))
        self.host_tier_models.set(len(mux.host_models()))
        self.hbm_bytes.set(mux.hbm_bytes_in_use)
        self.host_bytes.set(mux.store.bytes_used)


class HBMMetrics:
    """Unified-HBM-economy telemetry (`_hbm_*`; tpulab.hbm): per-tenant
    occupancy and claim-count gauges, the single headroom gauge, and the
    pressure-protocol counters (pressure rounds, forced KV demotions,
    forced model evictions, denials) — the view that says whether the
    device-memory economy is trading bytes productively (demotions +
    evictions, headroom near zero) or thrashing/denying (denials
    climbing, pressure rounds without reclaims).  Counters/gauges
    advance via :meth:`poll` over an
    :class:`~tpulab.hbm.HBMArbiter`."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.capacity_bytes = Gauge(
            f"{ns}_hbm_capacity_bytes",
            "Device-HBM budget the arbiter trades within",
            registry=self.registry)
        self.headroom_bytes = Gauge(
            f"{ns}_hbm_headroom_bytes",
            "THE headroom number: capacity minus every tenant's ledger "
            "claims (negative = over-committed discovery)",
            registry=self.registry)
        self.tenant_bytes = Gauge(
            f"{ns}_hbm_tenant_bytes",
            "Ledger bytes claimed per tenant (weights / kv / scratch)",
            ["tenant"], registry=self.registry)
        self.tenant_claims = Gauge(
            f"{ns}_hbm_tenant_claims",
            "Live ledger claims per tenant (models resident, pools, "
            "measured jits)", ["tenant"], registry=self.registry)
        self.pressure_events = Counter(
            f"{ns}_hbm_pressure_events",
            "Pressure rounds run (a request found no free headroom)",
            registry=self.registry)
        self.demotions = Counter(
            f"{ns}_hbm_demotions",
            "Pressure rounds where the KV tenant reclaimed (idle KV "
            "demoted to the host tier, pool shrunk)",
            registry=self.registry)
        self.evictions = Counter(
            f"{ns}_hbm_evictions",
            "Pressure rounds where the weights tenant reclaimed (cold "
            "unleased models swapped out)", registry=self.registry)
        self.denials = Counter(
            f"{ns}_hbm_denials",
            "Requests denied (timeout or nothing reclaimable) — the "
            "requester degraded to its static-budget behavior",
            registry=self.registry)
        self.grants = Counter(
            f"{ns}_hbm_grants", "Requests granted ledger bytes",
            registry=self.registry)
        self._last: Dict[str, int] = {}

    def _advance(self, counter, key: str, value: int) -> None:
        delta = value - self._last.get(key, 0)
        if delta > 0:
            counter.inc(delta)
        self._last[key] = value

    def poll(self, arbiter) -> None:
        """Sample an HBMArbiter (control-loop / poller hook)."""
        self.capacity_bytes.set(arbiter.capacity_bytes)
        self.headroom_bytes.set(arbiter.free_hbm_bytes)
        led = arbiter.ledger
        for tenant in led.tenants():
            self.tenant_bytes.labels(tenant=tenant).set(
                led.tenant_bytes(tenant))
            self.tenant_claims.labels(tenant=tenant).set(
                led.tenant_claims(tenant))
        self._advance(self.pressure_events, "pe", arbiter.pressure_events)
        self._advance(self.demotions, "dem", arbiter.demotions_forced)
        self._advance(self.evictions, "ev", arbiter.evictions_forced)
        self._advance(self.denials, "den", arbiter.denials)
        self._advance(self.grants, "gr", arbiter.grants)


class AdmissionMetrics:
    """Admission-control telemetry (`_admission_*`; serving/admission.py):
    admitted/rejected/shed counters keyed by tenant (and rejection
    reason), queue-wait-at-admission distribution, and live queue/inflight
    pressure gauges — the overload view docs/SERVING.md reads: *is the
    frontend shedding, whom, and why*."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.admitted = Counter(
            f"{ns}_admission_admitted_total", "Requests admitted",
            ["tenant"], registry=self.registry)
        self.rejected = Counter(
            f"{ns}_admission_rejected_total",
            "Requests rejected at admission, by reason (global_rate, "
            "tenant_rate, queue_full, shed, deadline, queue_timeout, "
            "chaos)", ["reason", "tenant"], registry=self.registry)
        self.shed = Counter(
            f"{ns}_admission_shed_total",
            "Queued requests shed for a higher-priority arrival",
            ["tenant"], registry=self.registry)
        self.queue_wait = Histogram(
            f"{ns}_admission_queue_wait_seconds",
            "Fair-queue wait of ADMITTED requests (arrival -> dispatch)",
            buckets=TTFT_BUCKETS, registry=self.registry)
        self.queue_depth = Gauge(
            f"{ns}_admission_queue_depth",
            "Requests waiting in the admission fair queue",
            registry=self.registry)
        self.inflight = Gauge(
            f"{ns}_admission_inflight",
            "Admitted requests currently holding a ticket",
            registry=self.registry)
        self._queue_wait_res = _Reservoir()

    # -- hooks (called by AdmissionController) ------------------------------
    def note_admitted(self, tenant: str, queue_wait_s: float) -> None:
        self.admitted.labels(tenant=tenant).inc()
        self.queue_wait.observe(max(0.0, queue_wait_s))
        self._queue_wait_res.observe(max(0.0, queue_wait_s))

    def note_rejected(self, reason: str, tenant: str) -> None:
        self.rejected.labels(reason=reason, tenant=tenant).inc()
        if reason == "shed":
            self.shed.labels(tenant=tenant).inc()

    def set_pressure(self, queued: int, inflight: int) -> None:
        self.queue_depth.set(queued)
        self.inflight.set(inflight)

    def queue_wait_quantiles(self) -> Dict[str, float]:
        """Exact sliding-window quantiles (bench.py's overload row)."""
        return {f"p{int(q * 100)}": self._queue_wait_res.quantile(q)
                for q in _QUANTILES}


class ChaosMetrics:
    """Fault-injection telemetry: one counter per (trip point, action), fed
    by the :func:`tpulab.chaos.set_observer` hook — a chaos experiment is
    then self-measuring (the injected-fault count sits on the same /metrics
    endpoint as the breaker/deadline reactions it provoked)."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        self.injections = Counter(
            f"{namespace}_chaos_injections_total",
            "Chaos rules fired, keyed by trip point and action",
            ["point", "action"], registry=self.registry)

    def observe(self, point: str, action: str) -> None:
        self.injections.labels(point=point, action=action).inc()

    def install(self) -> "ChaosMetrics":
        """Register as the process-wide chaos fire observer."""
        from tpulab import chaos
        chaos.set_observer(self.observe)
        return self

    def uninstall(self) -> None:
        from tpulab import chaos
        chaos.set_observer(None)


class SLOMetrics:
    """Per-tenant SLO telemetry (`_slo_*`; tpulab.obs.slo,
    docs/OBSERVABILITY.md "Fleet observability"): raw request/error/
    latency-breach counters per (tenant, request class) plus the
    multi-window burn-rate gauges — the "is tenant X meeting its SLO"
    scrape surface, and the alerting input the classic fast+slow
    multi-window burn alerts read."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.requests = Counter(
            f"{ns}_slo_requests_total",
            "SLO-accounted requests per tenant and request class "
            "(client-cancelled requests are excluded — neither good "
            "nor bad)", ["tenant", "request_class"],
            registry=self.registry)
        self.errors = Counter(
            f"{ns}_slo_errors_total",
            "Requests that failed the availability objective (terminal "
            "outcome not SUCCESS), per tenant and request class",
            ["tenant", "request_class"], registry=self.registry)
        self.latency_breaches = Counter(
            f"{ns}_slo_latency_breaches_total",
            "Requests whose end-to-end latency exceeded the objective, "
            "per tenant and request class",
            ["tenant", "request_class"], registry=self.registry)
        self.availability_burn = Gauge(
            f"{ns}_slo_availability_burn_rate",
            "Availability error-budget burn rate per tenant/class/"
            "window (1.0 = budget exhausted exactly over the objective "
            "period; >1 = burning early)",
            ["tenant", "request_class", "window"],
            registry=self.registry)
        self.latency_burn = Gauge(
            f"{ns}_slo_latency_burn_rate",
            "Latency error-budget burn rate per tenant/class/window",
            ["tenant", "request_class", "window"],
            registry=self.registry)

    # -- hooks (tpulab.obs.slo.SLOTracker) ------------------------------
    def note_request(self, tenant: str, request_class: str,
                     error: bool, breach: bool) -> None:
        self.requests.labels(tenant=tenant,
                             request_class=request_class).inc()
        if error:
            self.errors.labels(tenant=tenant,
                               request_class=request_class).inc()
        if breach:
            self.latency_breaches.labels(
                tenant=tenant, request_class=request_class).inc()

    def set_burn(self, tenant: str, request_class: str, window: str,
                 availability: float, latency: float) -> None:
        self.availability_burn.labels(
            tenant=tenant, request_class=request_class,
            window=window).set(float(availability))
        self.latency_burn.labels(
            tenant=tenant, request_class=request_class,
            window=window).set(float(latency))


class FederationMetrics:
    """Federated fleet view (`_fed_*`; tpulab.fleet.observer): the
    FleetObserver refreshes these replica-labeled gauges from each
    fleetz scrape's Status RPCs, so ONE /metrics endpoint on the
    observer node shows every replica's load/headroom/drain state
    side by side — the poor-operator's Prometheus federation.  Children
    for replicas that leave the snapshot are pruned on the next scrape
    (the stale-label-child discipline retire_replica follows)."""

    def __init__(self, namespace: str = "tpulab",
                 registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:  # pragma: no cover
            raise RuntimeError("prometheus_client unavailable")
        self.registry = registry or CollectorRegistry()
        ns = namespace
        self.scrapes = Counter(
            f"{ns}_fed_scrapes_total",
            "Federated fleet snapshots assembled by the observer",
            registry=self.registry)
        self.scrape_seconds = Gauge(
            f"{ns}_fed_scrape_seconds",
            "Wall-clock cost of the last federated snapshot (all "
            "replica Status RPCs + assembly)", registry=self.registry)
        self.replicas = Gauge(
            f"{ns}_fed_replicas",
            "Replicas in the last federated snapshot",
            registry=self.registry)
        self.up = Gauge(
            f"{ns}_fed_replica_up",
            "1 when the replica answered its Status RPC in the last "
            "snapshot, else 0", ["replica"], registry=self.registry)
        self.inflight = Gauge(
            f"{ns}_fed_replica_inflight",
            "Server-reported in-flight requests per replica",
            ["replica"], registry=self.registry)
        self.queued = Gauge(
            f"{ns}_fed_replica_queued",
            "Server-reported queued requests per replica",
            ["replica"], registry=self.registry)
        self.free_hbm_bytes = Gauge(
            f"{ns}_fed_replica_free_hbm_bytes",
            "Server-reported free HBM headroom per replica",
            ["replica"], registry=self.registry)
        self.free_kv_pages = Gauge(
            f"{ns}_fed_replica_free_kv_pages",
            "Server-reported free KV-cache pages per replica",
            ["replica"], registry=self.registry)
        self.draining = Gauge(
            f"{ns}_fed_replica_draining",
            "1 while the replica reports itself draining, else 0",
            ["replica"], registry=self.registry)
        self.prefix_hits = Gauge(
            f"{ns}_fed_replica_prefix_hits",
            "Server-reported lifetime prefix-cache hits per replica",
            ["replica"], registry=self.registry)
        self.prefix_lookups = Gauge(
            f"{ns}_fed_replica_prefix_lookups",
            "Server-reported lifetime prefix-cache lookups per replica",
            ["replica"], registry=self.registry)
        self.resident_models = Gauge(
            f"{ns}_fed_replica_resident_models",
            "Models resident in device memory per replica",
            ["replica"], registry=self.registry)
        self._seen: set = set()
        self._per_replica = (self.up, self.inflight, self.queued,
                             self.free_hbm_bytes, self.free_kv_pages,
                             self.draining, self.prefix_hits,
                             self.prefix_lookups, self.resident_models)

    # -- hooks (tpulab.fleet.observer.FleetObserver) --------------------
    def observe_scrape(self, seconds: float, replicas: int) -> None:
        self.scrapes.inc()
        self.scrape_seconds.set(float(seconds))
        self.replicas.set(int(replicas))

    def set_replica(self, replica: str, up: bool, inflight: int = 0,
                    queued: int = 0, free_hbm_bytes: int = 0,
                    free_kv_pages: int = 0, draining: bool = False,
                    prefix_hits: int = 0, prefix_lookups: int = 0,
                    resident_models: int = 0) -> None:
        self._seen.add(replica)
        self.up.labels(replica=replica).set(1 if up else 0)
        self.inflight.labels(replica=replica).set(int(inflight))
        self.queued.labels(replica=replica).set(int(queued))
        self.free_hbm_bytes.labels(replica=replica).set(
            int(free_hbm_bytes))
        self.free_kv_pages.labels(replica=replica).set(
            int(free_kv_pages))
        self.draining.labels(replica=replica).set(1 if draining else 0)
        self.prefix_hits.labels(replica=replica).set(int(prefix_hits))
        self.prefix_lookups.labels(replica=replica).set(
            int(prefix_lookups))
        self.resident_models.labels(replica=replica).set(
            int(resident_models))

    def prune(self, keep) -> None:
        """Drop label children for replicas no longer in the snapshot —
        a retired replica must stop exporting, not freeze at its last
        value."""
        for replica in self._seen - set(keep):
            for g in self._per_replica:
                try:
                    g.remove(replica)
                except KeyError:  # pragma: no cover - never created
                    pass
        self._seen &= set(keep)


class MultiRegistryCollector:
    """Aggregating collector: exposes several CollectorRegistry instances
    through one registry (hence one /metrics port).  Metric names must be
    disjoint across the sub-registries — true by construction for the
    collectors in this module (``_request_*`` / ``_replica_*`` / ``_llm_*``
    / ``_admission_*`` / ``_kv_tier_*`` / ``_chaos_*`` prefixes)."""

    def __init__(self, registries: Sequence["CollectorRegistry"]):
        self._registries = list(registries)

    def collect(self):
        for reg in self._registries:
            yield from reg.collect()


def start_metrics_server(metrics, port: int = 9090):
    """Expose /metrics (reference Exposer on :8080).

    ``metrics`` is a metrics holder with a ``registry`` attribute
    (InferenceMetrics, ReplicaSetMetrics, GenerationMetrics, ChaosMetrics,
    ...), a bare CollectorRegistry, or a list/tuple of either — multiple
    holders are aggregated behind ONE port via
    :class:`MultiRegistryCollector` (a serving process exports its
    request, routing, generation and chaos telemetry from a single
    scrape target)."""
    if isinstance(metrics, (list, tuple)):
        agg = CollectorRegistry()
        agg.register(MultiRegistryCollector(
            [getattr(m, "registry", m) for m in metrics]))
        return start_http_server(port, registry=agg)
    return start_http_server(port, registry=getattr(metrics, "registry",
                                                    metrics))
