"""tpulab.utils — flags, metrics, logging helpers."""

from tpulab.utils.metrics import InferenceMetrics, start_metrics_server

__all__ = ["InferenceMetrics", "start_metrics_server"]
