"""The dynamic-batching state machine (reference batcher.h:23-154).

``StandardBatcher`` is *pure state* — no threads, no locks, no timers
(exactly like the reference): ``enqueue(item)`` returns a shared future tied
to the batch the item joined; ``update()`` closes the batch when full;
``close_batch()`` closes it unconditionally (the timeout path).  All policy
(who calls close, on which thread, after what window) lives in the
:mod:`dispatcher`.

One promise per batch: every item in a batch shares the same future
(reference batcher.h:100-116).
"""

from __future__ import annotations

import itertools
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Generic, List, Optional, TypeVar

T = TypeVar("T")


@dataclass
class Batch(Generic[T]):
    """A closed batch: items + the promise completing them
    (reference Batcher::Batch{items, promise, batch_id})."""

    batch_id: int
    items: List[T]
    future: Future = field(default_factory=Future)

    def complete(self, result=None) -> None:
        self.future.set_result(result)

    def fail(self, exc: BaseException) -> None:
        self.future.set_exception(exc)

    def __len__(self) -> int:
        return len(self.items)


class StandardBatcher(Generic[T]):
    """Batching state machine (reference StandardBatcher<T, ThreadType>)."""

    def __init__(self, max_batch_size: int):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.max_batch_size = max_batch_size
        self._ids = itertools.count()
        self._open: Optional[Batch[T]] = None

    @property
    def current_batch_id(self) -> Optional[int]:
        return self._open.batch_id if self._open else None

    @property
    def current_batch_size(self) -> int:
        return len(self._open.items) if self._open else 0

    def enqueue(self, item: T) -> Future:
        """Add item to the open batch; returns that batch's shared future."""
        if self._open is None:
            self._open = Batch(next(self._ids), [])
        self._open.items.append(item)
        return self._open.future

    def update(self) -> Optional[Batch[T]]:
        """Close and return the batch iff full (reference update())."""
        if self._open is not None and len(self._open.items) >= self.max_batch_size:
            return self.close_batch()
        return None

    def close_batch(self) -> Optional[Batch[T]]:
        """Unconditionally close the open batch (timeout path)."""
        batch, self._open = self._open, None
        return batch

    def empty(self) -> bool:
        return self._open is None
