"""Promise-fulfilling callable wrappers (reference async_compute.h:38-118).

``async_compute(fn)`` returns a :class:`SharedPackagedTask`: a callable whose
invocation runs ``fn`` and fulfills a shared future with its result — the glue
the reference uses between pipeline stages and RPC client completions.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Callable, Generic, TypeVar

R = TypeVar("R")


class SharedPackagedTask(Generic[R]):
    """Callable binding a user fn to a promise (reference shared_packaged_task)."""

    def __init__(self, fn: Callable[..., R]):
        self._fn = fn
        self._future: Future = Future()

    def get_future(self) -> Future:
        return self._future

    def __call__(self, *args, **kwargs) -> None:
        if self._future.done():
            raise RuntimeError("SharedPackagedTask already invoked")
        try:
            self._future.set_result(self._fn(*args, **kwargs))
        except BaseException as e:  # noqa: BLE001 - promise semantics
            self._future.set_exception(e)


def async_compute(fn: Callable[..., R]) -> SharedPackagedTask[R]:
    """Reference ``async_compute<void(Args...)>::wrap(f)``."""
    return SharedPackagedTask(fn)
