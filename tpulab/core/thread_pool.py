"""Work-queue thread pool with CPU-affinity constructors
(reference thread_pool.h:73-298; affinity ctors 94-116, CreateThread 255-274).

Three construction modes, as in the reference:
- ``ThreadPool(n)`` — N workers, no pinning
- ``ThreadPool(n, cpus=CpuSet)`` — N workers all sharing one affinity mask
- ``ThreadPool.one_per_cpu(cpus)`` — one worker pinned to each CPU in the set
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

from tpulab.core.affinity import Affinity, CpuSet


class ThreadPool:
    """Classic work-queue pool (reference BaseThreadPool/ThreadPool)."""

    def __init__(self, n_threads: int, cpus: Optional[CpuSet] = None,
                 name: str = "pool"):
        if n_threads < 1:
            raise ValueError("need at least one thread")
        self._tasks: "queue.Queue" = queue.Queue()
        self._shutdown = False
        self._state_lock = threading.Lock()
        self._name = name
        self._threads: List[threading.Thread] = []
        for i in range(n_threads):
            self._spawn(f"{name}-{i}", cpus)

    @classmethod
    def one_per_cpu(cls, cpus: CpuSet, name: str = "pool") -> "ThreadPool":
        """One thread pinned per CPU (reference thread_pool.h:108-116)."""
        if not cpus:
            raise ValueError("one_per_cpu requires a non-empty CpuSet")
        self = cls.__new__(cls)
        self._tasks = queue.Queue()
        self._shutdown = False
        self._state_lock = threading.Lock()
        self._name = name
        self._threads = []
        for cpu in cpus:
            self._spawn(f"{name}-cpu{cpu}", CpuSet([cpu]))
        return self

    def _spawn(self, name: str, cpus: Optional[CpuSet]) -> None:
        t = threading.Thread(target=self._worker, args=(cpus,), name=name,
                             daemon=True)
        self._threads.append(t)
        t.start()

    def _worker(self, cpus: Optional[CpuSet]) -> None:
        if cpus:
            try:
                Affinity.set_affinity(cpus)
            except OSError:  # cpu not in this cgroup — degrade gracefully
                pass
        while True:
            task = self._tasks.get()
            if task is None:
                return
            fn, args, kwargs, fut = task
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_result(fn(*args, **kwargs))
                except BaseException as e:  # noqa: BLE001 - promise semantics
                    fut.set_exception(e)

    @property
    def size(self) -> int:
        return len(self._threads)

    def enqueue(self, fn: Callable, *args, **kwargs) -> Future:
        """Submit work; returns a future (reference ThreadPool::enqueue)."""
        fut: Future = Future()
        # the flag check and the put are one atomic step: a task enqueued
        # behind shutdown sentinels would never run and never resolve
        with self._state_lock:
            if self._shutdown:
                raise RuntimeError("enqueue on stopped ThreadPool")
            self._tasks.put((fn, args, kwargs, fut))
        return fut

    submit = enqueue  # concurrent.futures-style alias

    def shutdown(self, wait: bool = True) -> None:
        with self._state_lock:
            if self._shutdown:
                return
            self._shutdown = True
            for _ in self._threads:
                self._tasks.put(None)
        if wait:
            for t in self._threads:
                t.join(timeout=10)

    def __enter__(self) -> "ThreadPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
