"""Thread-type policies + the userspace execution domain.

The reference selects sync primitives at compile time between ``std::`` and
``boost::fibers::`` (reference standard_threads.h:1-40,
userspace_threads.h:1-42) so one Pool/Batcher implementation serves both OS
threads and fibers.  The Python-native mapping:

- ``standard_threads``: ``threading`` primitives + ``concurrent.futures.Future``.
- ``userspace_threads``: asyncio primitives + ``asyncio`` futures.  Fibers in
  the reference exist so request handlers can *block* on pool pops and device
  sync without stalling OS threads; in Python the same property comes from
  awaiting inside an event loop.  Components with fiber specializations in the
  reference (Pool, Dispatcher, sync) therefore expose ``*_async`` variants
  usable under this policy.

``EventLoopGroup`` is the ``FiberGroup`` analog (reference fiber_group.h:9-51):
N OS threads each running an asyncio loop, forming a userspace execution
domain with work-sharing submission.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import itertools
import threading
from typing import Awaitable, Callable, Optional, Sequence


class standard_threads:
    """OS-thread policy (reference standard_threads.h)."""

    Mutex = threading.Lock
    RecursiveMutex = threading.RLock
    Condition = threading.Condition
    Future = concurrent.futures.Future

    @staticmethod
    def make_future() -> concurrent.futures.Future:
        return concurrent.futures.Future()

    @staticmethod
    def async_(fn: Callable, *args) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 - promise semantics
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    @staticmethod
    def sleep(seconds: float) -> None:
        import time
        time.sleep(seconds)


class userspace_threads:
    """Event-loop (fiber-analog) policy (reference userspace_threads.h)."""

    Mutex = asyncio.Lock
    Condition = asyncio.Condition

    @staticmethod
    def make_future() -> asyncio.Future:
        return asyncio.get_event_loop().create_future()

    @staticmethod
    def async_(coro: Awaitable) -> "asyncio.Task":
        return asyncio.get_event_loop().create_task(coro)

    @staticmethod
    async def sleep(seconds: float) -> None:
        await asyncio.sleep(seconds)


class EventLoopGroup:
    """N OS threads running asyncio loops — the FiberGroup analog
    (reference fiber_group.h:9-51, algo::shared_work scheduler).

    ``submit(coro)`` schedules onto the least-recently-used loop (round-robin
    work sharing); ``submit_fn`` wraps a plain callable.  All loops drain and
    join on ``shutdown()``/context exit.
    """

    def __init__(self, n_threads: int, name: str = "elg"):
        if n_threads < 1:
            raise ValueError("need at least one thread")
        self._loops: list[asyncio.AbstractEventLoop] = []
        self._threads: list[threading.Thread] = []
        self._rr = itertools.cycle(range(n_threads))
        self._started = threading.Barrier(n_threads + 1)
        for i in range(n_threads):
            t = threading.Thread(target=self._run_loop, name=f"{name}-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        self._started.wait()

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loops.append(loop)
        self._started.wait()
        loop.run_forever()
        # drain pending callbacks then close
        loop.run_until_complete(loop.shutdown_asyncgens())
        loop.close()

    @property
    def size(self) -> int:
        return len(self._threads)

    def submit(self, coro: Awaitable) -> concurrent.futures.Future:
        """Schedule a coroutine on the next loop; thread-safe."""
        loop = self._loops[next(self._rr)]
        return asyncio.run_coroutine_threadsafe(coro, loop)

    def submit_fn(self, fn: Callable, *args) -> concurrent.futures.Future:
        async def runner():
            return fn(*args)
        return self.submit(runner())

    def shutdown(self) -> None:
        for loop in self._loops:
            loop.call_soon_threadsafe(loop.stop)
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self) -> "EventLoopGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
