"""DLPack-based dtype descriptors (reference types.h:40-139, types.cc).

``DType`` wraps a DLPack {code, bits, lanes} triple with byte size and numpy
interop — the framework's common currency for binding specs, wire tensors, and
JAX array dtypes.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum

import numpy as np


class DLDataTypeCode(IntEnum):
    kDLInt = 0
    kDLUInt = 1
    kDLFloat = 2
    kDLBfloat = 4


_DL_TO_NUMPY = {
    (DLDataTypeCode.kDLFloat, 16): np.float16,
    (DLDataTypeCode.kDLFloat, 32): np.float32,
    (DLDataTypeCode.kDLFloat, 64): np.float64,
    (DLDataTypeCode.kDLInt, 8): np.int8,
    (DLDataTypeCode.kDLInt, 16): np.int16,
    (DLDataTypeCode.kDLInt, 32): np.int32,
    (DLDataTypeCode.kDLInt, 64): np.int64,
    (DLDataTypeCode.kDLUInt, 8): np.uint8,
    (DLDataTypeCode.kDLUInt, 16): np.uint16,
    (DLDataTypeCode.kDLUInt, 32): np.uint32,
    (DLDataTypeCode.kDLUInt, 64): np.uint64,
}


@dataclass(frozen=True)
class DType:
    """DLPack data type (reference dtype wrapping DLDataType)."""

    code: DLDataTypeCode
    bits: int
    lanes: int = 1

    @property
    def itemsize(self) -> int:
        return (self.bits * self.lanes + 7) // 8

    # -- numpy interop ------------------------------------------------------
    def to_numpy(self) -> np.dtype:
        key = (self.code, self.bits)
        if key == (DLDataTypeCode.kDLBfloat, 16):
            import ml_dtypes
            return np.dtype(ml_dtypes.bfloat16)
        if key not in _DL_TO_NUMPY:
            raise TypeError(f"no numpy equivalent for {self}")
        return np.dtype(_DL_TO_NUMPY[key])

    def is_compatible(self, np_dtype) -> bool:
        """Numpy-compat check (reference dtype::is_compatible)."""
        try:
            return np.dtype(np_dtype) == self.to_numpy()
        except TypeError:
            return False

    def __str__(self) -> str:
        code = {0: "int", 1: "uint", 2: "float", 4: "bfloat"}[int(self.code)]
        suffix = f"x{self.lanes}" if self.lanes != 1 else ""
        return f"{code}{self.bits}{suffix}"


# canonical instances (reference ArrayType<T> table)
int8 = DType(DLDataTypeCode.kDLInt, 8)
int16 = DType(DLDataTypeCode.kDLInt, 16)
int32 = DType(DLDataTypeCode.kDLInt, 32)
int64 = DType(DLDataTypeCode.kDLInt, 64)
uint8 = DType(DLDataTypeCode.kDLUInt, 8)
uint16 = DType(DLDataTypeCode.kDLUInt, 16)
uint32 = DType(DLDataTypeCode.kDLUInt, 32)
uint64 = DType(DLDataTypeCode.kDLUInt, 64)
float16 = DType(DLDataTypeCode.kDLFloat, 16)
float32 = DType(DLDataTypeCode.kDLFloat, 32)
float64 = DType(DLDataTypeCode.kDLFloat, 64)
bfloat16 = DType(DLDataTypeCode.kDLBfloat, 16)


def dtype_from_numpy(np_dtype) -> DType:
    """Map a numpy (or ml_dtypes) dtype to a DType."""
    d = np.dtype(np_dtype)
    if d.name == "bfloat16":
        return bfloat16
    table = {
        "float16": float16, "float32": float32, "float64": float64,
        "int8": int8, "int16": int16, "int32": int32, "int64": int64,
        "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    }
    if d.name not in table:
        raise TypeError(f"unsupported numpy dtype {d}")
    return table[d.name]
