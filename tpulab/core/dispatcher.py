"""Dispatcher: batching + concurrency + timeout control
(reference dispatcher.h:29-333).

Wraps a :class:`~tpulab.core.batcher.StandardBatcher` with execution policy:

- :class:`Dispatcher` — the std-threads specialization (reference
  dispatcher.h:29-180): closed batches run on a worker :class:`ThreadPool`;
  window timeouts fire from a :class:`DeferredShortTaskPool` progress task
  keyed on a dispatch id so stale timers are ignored.
- :class:`AsyncDispatcher` — the fiber specialization (reference
  dispatcher.h:184-333): lives inside an event loop; each closed batch is a
  detached task (QueueBatch:271-282) and the window timeout is a sleeping
  task (QueueProgressTask:284-294) — the asyncio mapping of detached fibers.

``execute_fn(items, completer)`` computes a batch and calls
``completer(result)`` (or ``completer.fail(exc)``) to wake all waiters.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import Future
from typing import Awaitable, Callable, Generic, List, Optional, TypeVar

from tpulab.core.batcher import Batch, StandardBatcher
from tpulab.core.task_pool import DeferredShortTaskPool
from tpulab.core.thread_pool import ThreadPool

T = TypeVar("T")


class Completer:
    """Completion handle passed to execute_fn (reference completer callable)."""

    __slots__ = ("_batch",)

    def __init__(self, batch: Batch):
        self._batch = batch

    def __call__(self, result=None) -> None:
        self._batch.complete(result)

    def fail(self, exc: BaseException) -> None:
        self._batch.fail(exc)


class Dispatcher(Generic[T]):
    """std-threads dispatcher (reference dispatcher.h:29-180)."""

    def __init__(self, max_batch_size: int, window_s: float,
                 execute_fn: Callable[[List[T], Completer], None],
                 workers: Optional[ThreadPool] = None, n_workers: int = 1):
        self._batcher: StandardBatcher[T] = StandardBatcher(max_batch_size)
        self._window = window_s
        self._execute = execute_fn
        self._own_workers = workers is None
        self._workers = workers or ThreadPool(n_workers, name="dispatch")
        self._timers = DeferredShortTaskPool(name="dispatch-timer")
        self._lock = threading.Lock()

    def enqueue(self, item: T) -> Future:
        """Thread-safe enqueue (reference dispatcher.h:79-104, under mutex)."""
        with self._lock:
            first_in_batch = self._batcher.empty()
            fut = self._batcher.enqueue(item)
            batch_id = self._batcher.current_batch_id
            batch = self._batcher.update()
        if batch is not None:
            self._queue_batch(batch)
        elif first_in_batch:
            # arm the window timer for this dispatch id (ProgressTask keying,
            # reference dispatcher.h:140-170)
            self._timers.enqueue_deferred(
                self._window, lambda: self._progress_task(batch_id))
        return fut

    def _progress_task(self, batch_id: int) -> None:
        with self._lock:
            if self._batcher.current_batch_id != batch_id:
                return  # stale timer — batch already closed by size
            batch = self._batcher.close_batch()
        if batch is not None:
            self._queue_batch(batch)

    def _queue_batch(self, batch: Batch) -> None:
        self._workers.enqueue(self._run_batch, batch)

    def _run_batch(self, batch: Batch) -> None:
        completer = Completer(batch)
        try:
            self._execute(batch.items, completer)
        except BaseException as e:  # noqa: BLE001
            if not batch.future.done():
                completer.fail(e)

    def flush(self) -> None:
        """Close any open batch immediately (drain path)."""
        with self._lock:
            batch = self._batcher.close_batch()
        if batch is not None:
            self._queue_batch(batch)

    def shutdown(self) -> None:
        self.flush()
        self._timers.shutdown()
        if self._own_workers:
            self._workers.shutdown()

    def __enter__(self) -> "Dispatcher[T]":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


class AsyncDispatcher(Generic[T]):
    """Event-loop (fiber-analog) dispatcher (reference dispatcher.h:184-333).

    Use from a single event loop.  ``execute_fn`` may be sync or async; each
    closed batch runs as a detached task.
    """

    def __init__(self, max_batch_size: int, window_s: float,
                 execute_fn: Callable[[List[T], Completer], Optional[Awaitable]]):
        self._batcher: StandardBatcher[T] = StandardBatcher(max_batch_size)
        self._window = window_s
        self._execute = execute_fn
        self._tasks: set = set()
        self._timer: Optional[asyncio.Task] = None

    def enqueue(self, item: T) -> asyncio.Future:
        """Must be called from the owning event loop."""
        loop = asyncio.get_running_loop()
        first_in_batch = self._batcher.empty()
        cf = self._batcher.enqueue(item)
        batch_id = self._batcher.current_batch_id
        batch = self._batcher.update()
        if batch is not None:
            self._cancel_timer()
            self._queue_batch(batch)
        elif first_in_batch:
            self._timer = asyncio.get_running_loop().create_task(
                self._progress_task(batch_id))
        return asyncio.wrap_future(cf, loop=loop)

    async def _progress_task(self, batch_id: int) -> None:
        """Sleeping progress fiber (reference dispatcher.h:285-294)."""
        try:
            await asyncio.sleep(self._window)
        except asyncio.CancelledError:
            return  # batch closed by size — stale timer
        if self._batcher.current_batch_id != batch_id:
            return
        batch = self._batcher.close_batch()
        if batch is not None:
            self._queue_batch(batch)

    def _cancel_timer(self) -> None:
        if self._timer is not None and not self._timer.done():
            self._timer.cancel()
        self._timer = None

    def _queue_batch(self, batch: Batch) -> None:
        self._detach(self._run_batch(batch))

    async def _run_batch(self, batch: Batch) -> None:
        completer = Completer(batch)
        try:
            result = self._execute(batch.items, completer)
            if asyncio.iscoroutine(result):
                await result
        except BaseException as e:  # noqa: BLE001
            if not batch.future.done():
                completer.fail(e)

    def _detach(self, coro) -> None:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def flush(self) -> None:
        self._cancel_timer()
        batch = self._batcher.close_batch()
        if batch is not None:
            self._queue_batch(batch)
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
