"""Single-thread deadline scheduler (reference task_pool.h:36-113).

A map of deadline -> tasks serviced by one thread doing ``cv.wait_until`` on
the earliest deadline — used by the Dispatcher for batching-window timeouts.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional


class DeferredShortTaskPool:
    """Deadline-ordered task runner (reference DeferredShortTaskPool).

    Tasks must be short: they run on the scheduler thread.
    """

    def __init__(self, name: str = "deferred"):
        self._heap: list = []  # (deadline, seq, fn)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._shutdown = False
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def enqueue_deferred(self, delay_s: float, fn: Callable[[], None]) -> None:
        """Run ``fn`` after ``delay_s`` seconds (reference enqueue_deferred)."""
        self.enqueue_at(time.monotonic() + max(0.0, delay_s), fn)

    def enqueue_at(self, deadline: float, fn: Callable[[], None]) -> None:
        with self._cv:
            if self._shutdown:
                raise RuntimeError("enqueue on stopped DeferredShortTaskPool")
            heapq.heappush(self._heap, (deadline, next(self._seq), fn))
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._shutdown and not self._heap:
                    self._cv.wait()
                if self._shutdown and not self._heap:
                    return
                deadline, _seq, fn = self._heap[0]
                now = time.monotonic()
                if deadline > now:
                    self._cv.wait(timeout=deadline - now)
                    continue
                heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # pragma: no cover - keep scheduler alive
                import logging
                logging.getLogger("tpulab.core").exception("deferred task failed")

    def shutdown(self, drain: bool = False) -> None:
        with self._cv:
            self._shutdown = True
            if not drain:
                self._heap.clear()
            self._cv.notify()
        self._thread.join(timeout=10)

    def __enter__(self) -> "DeferredShortTaskPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
