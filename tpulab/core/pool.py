"""Blocking resource pools with return-to-pool handles.

The reference iterated four pool designs (reference pool.h:51-775); this is
the v4 surface (``pop_shared``/``pop_unique``, ``UniquePool``) with the v1
deleter trick (pool.h:192-204) expressed as a context-manager/finalizer handle:
popping returns a ``PoolItem`` whose close/GC returns the resource to the pool,
keeping the pool alive via a strong reference.  ``Pool.pop()`` blocks when
empty — this is the backpressure mechanism the InferenceManager builds on
(reference inference_manager.cc:232-273).

``pop_async()`` is the fiber-policy variant (usable from event-loop handlers,
the FiberExecutor path) — it awaits without blocking the OS thread.
"""

from __future__ import annotations

import collections
import threading
import weakref
from concurrent.futures import Future
from typing import Any, Callable, Generic, Iterable, Optional, TypeVar

T = TypeVar("T")


class Queue(Generic[T]):
    """Mutex+CV blocking FIFO (reference pool.h Queue<T>:51-120)."""

    def __init__(self):
        self._items: collections.deque[T] = collections.deque()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def push(self, item: T) -> None:
        with self._cv:
            self._items.append(item)
            self._cv.notify()

    def pop(self, timeout: Optional[float] = None) -> T:
        with self._cv:
            if not self._cv.wait_for(lambda: len(self._items) > 0, timeout):
                raise TimeoutError("Queue.pop timed out")
            return self._items.popleft()

    def try_pop(self) -> Optional[T]:
        with self._cv:
            return self._items.popleft() if self._items else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def empty(self) -> bool:
        return len(self) == 0


class PoolItem(Generic[T]):
    """RAII handle: returns the resource on close/GC (reference v1 deleter
    trick pool.h:192-204 / v3 Resource wrapper pool.h:356-452)."""

    __slots__ = ("_value", "_returned", "_finalizer", "__weakref__")

    def __init__(self, value: T, return_fn: Callable[[T], None]):
        self._value = value
        self._returned = False
        self._finalizer = weakref.finalize(self, return_fn, value)

    def get(self) -> T:
        if self._returned:
            raise RuntimeError("pool item already returned")
        return self._value

    #: dereference sugar: item.value
    @property
    def value(self) -> T:
        return self.get()

    def release(self) -> None:
        """Return the resource to the pool now."""
        if not self._returned:
            self._returned = True
            self._finalizer()

    close = release

    def detach(self) -> T:
        """Take the resource out of pool management permanently."""
        if self._returned:
            raise RuntimeError("pool item already returned")
        self._returned = True
        self._finalizer.detach()
        return self._value

    def __enter__(self) -> T:
        return self.get()

    def __exit__(self, *exc) -> None:
        self.release()


class Pool(Generic[T]):
    """Shared resource pool (reference v4::Pool pool.h:454-638).

    - ``push(item)`` adds a resource
    - ``pop()`` blocks until available, returns a :class:`PoolItem`
    - ``pop_async()`` awaitable variant for event-loop (fiber) handlers
    - ``on_return`` hook runs as the item re-enters the pool (Reset semantics)
    """

    def __init__(self, items: Iterable[T] = (),
                 on_return: Optional[Callable[[T], None]] = None):
        self._queue: Queue[T] = Queue()
        self._on_return = on_return
        self._waiters: collections.deque = collections.deque()
        self._waiter_lock = threading.Lock()
        self._size = 0
        for it in items:
            self.push(it)

    @classmethod
    def create(cls, *args, **kwargs) -> "Pool[T]":
        return cls(*args, **kwargs)

    @property
    def size(self) -> int:
        """Total resources owned (in pool + checked out)."""
        return self._size

    @property
    def available(self) -> int:
        return len(self._queue)

    def push(self, item: T) -> None:
        with self._waiter_lock:  # size must not drift under concurrent push
            self._size += 1
        self._return(item, run_hook=False)

    def _return(self, item: T, run_hook: bool = True) -> None:
        if run_hook and self._on_return is not None:
            self._on_return(item)
        # Hand directly to an async waiter if any, else queue.  The push must
        # happen under the waiter lock: pop_async registers waiters under the
        # same lock after re-checking the queue, so serializing check+push
        # here closes the lost-wakeup window.
        with self._waiter_lock:
            while self._waiters:
                fut, loop = self._waiters.popleft()
                if not fut.done():
                    loop.call_soon_threadsafe(self._deliver, fut, item)
                    return
            self._queue.push(item)

    def _deliver(self, fut, item: T) -> None:
        # Runs on the waiter's loop. If the waiter was cancelled in the
        # meantime, the resource must not be lost — return it properly so
        # another waiter (or the queue) gets it.
        if fut.done():
            self._return(item, run_hook=False)
        else:
            fut.set_result(item)

    def pop(self, timeout: Optional[float] = None,
            on_return: Optional[Callable[[T], None]] = None) -> PoolItem[T]:
        """Blocking pop (reference pop_shared). MAY BLOCK — backpressure point."""
        value = self._queue.pop(timeout)
        extra = on_return

        def return_fn(v: T) -> None:
            if extra is not None:
                extra(v)
            self._return(v)

        return PoolItem(value, return_fn)

    async def pop_async(self) -> PoolItem[T]:
        """Event-loop pop (the fiber-policy specialization)."""
        import asyncio
        value = self._queue.try_pop()
        if value is None:
            loop = asyncio.get_running_loop()
            fut: asyncio.Future = loop.create_future()
            with self._waiter_lock:
                # re-check under lock to avoid a lost wakeup
                value = self._queue.try_pop()
                if value is None:
                    self._waiters.append((fut, loop))
            if value is None:
                value = await fut
        return PoolItem(value, self._return)

    def try_pop(self) -> Optional[PoolItem[T]]:
        value = self._queue.try_pop()
        if value is None:
            return None
        return PoolItem(value, self._return)


class NativeBackedPool(Generic[T]):
    """Pool with :class:`Pool`'s surface whose blocking queue is the native
    futex TokenPool (cpp/src/pool.cc, bound via tpulab.native).

    Pops and pushes park in C with the GIL released — on the serving hot
    path (three pop/release pairs per request: buffers, global token, model
    slot) this removes the Python condition-variable wakeup cost and the
    GIL thrash between the pipeline's stage threads.  Items live in a
    Python-side slot table; the native pool carries slot indices.
    """

    def __init__(self, items: Iterable[T] = (),
                 on_return: Optional[Callable[[T], None]] = None):
        from tpulab import native
        if not native.available():
            raise RuntimeError("native library not built "
                               "(cmake -S cpp -B cpp/build -G Ninja)")
        self._native = native.NativeTokenPool()
        self._items: list = []
        self._on_return = on_return
        self._lock = threading.Lock()
        for it in items:
            self.push(it)

    @property
    def size(self) -> int:
        """Total resources owned (in pool + checked out)."""
        return len(self._items)

    @property
    def available(self) -> int:
        return len(self._native)

    def push(self, item: T) -> None:
        with self._lock:
            idx = len(self._items)
            self._items.append(item)
        self._native.push(idx)

    def _return_idx(self, idx: int, run_hook: bool = True) -> None:
        if run_hook and self._on_return is not None:
            self._on_return(self._items[idx])
        self._native.push(idx)

    def _make_item(self, idx: int,
                   extra: Optional[Callable[[T], None]]) -> PoolItem[T]:
        value = self._items[idx]

        def return_fn(v: T) -> None:
            if extra is not None:
                extra(v)
            self._return_idx(idx)

        return PoolItem(value, return_fn)

    def pop(self, timeout: Optional[float] = None,
            on_return: Optional[Callable[[T], None]] = None) -> PoolItem[T]:
        """Blocking pop (futex wait in C, GIL released). MAY BLOCK."""
        idx = self._native.pop(timeout)
        return self._make_item(idx, on_return)

    def try_pop(self) -> Optional[PoolItem[T]]:
        idx = self._native.try_pop()
        if idx is None:
            return None
        return self._make_item(idx, None)

    async def pop_async(self) -> PoolItem[T]:
        """Event-loop pop: fast path via try_pop, else the blocking native
        pop rides the default executor (the loop thread never blocks).

        A dedicated daemon thread polls the native pop with a bounded
        timeout (clean interpreter exit) and hands any won index to the
        loop explicitly — a cancelled waiter's index is re-returned to the
        pool, never leaked (Pool._deliver's guarantee; asyncio's
        run_in_executor would silently drop the result of a cancelled
        wrapper future, so it cannot be used here)."""
        import asyncio
        idx = self._native.try_pop()
        if idx is None:
            loop = asyncio.get_running_loop()
            afut: "asyncio.Future[int]" = loop.create_future()

            def deliver(idx2: int) -> None:  # runs on the loop
                if afut.done():  # cancelled meanwhile: back to the pool
                    self._return_idx(idx2, run_hook=False)
                else:
                    afut.set_result(idx2)

            def worker() -> None:
                while True:
                    try:
                        idx2 = self._native.pop(timeout=0.5)
                    except TimeoutError:
                        if afut.cancelled():
                            return  # waiter gone, nothing won
                        continue
                    try:
                        loop.call_soon_threadsafe(deliver, idx2)
                    except RuntimeError:  # loop already closed
                        self._return_idx(idx2, run_hook=False)
                    return

            threading.Thread(target=worker, name="native-pool-wait",
                             daemon=True).start()
            idx = await afut
        return self._make_item(idx, None)


def make_serving_pool(items: Iterable[T] = (),
                      on_return: Optional[Callable[[T], None]] = None,
                      prefer_native: bool = True):
    """Native futex pool when the C++ core is built, else the Python Pool.

    ``TPULAB_NO_NATIVE=1`` forces the Python fallback (A/B benching).
    """
    if prefer_native:
        try:
            from tpulab import native
            if native.enabled():
                return NativeBackedPool(items, on_return)
        except Exception:  # pragma: no cover - fall back on any load issue
            pass
    return Pool(items, on_return)


class UniquePool(Pool[T]):
    """Pool whose items are exclusively owned while out
    (reference v4::UniquePool pool.h:640-775).  In Python exclusivity is by
    convention — ``pop_unique`` returns the same RAII handle but ``detach`` is
    the supported way to take ownership out."""

    pop_unique = Pool.pop
