"""Sliding-window streaming compute over a cyclic buffer
(reference cyclic_windowed_buffer.h:59-440: impl 136-244, executor 369-440,
reservation 287-365; v1 cyclic_buffer.h subsumed).

A buffer is divided into ``window_count`` windows of ``window_size`` bytes,
each overlapping its predecessor by ``overlap`` bytes (stride =
window_size - overlap; buffer size = count*stride + overlap).  Appending data
fills windows in sequence; each filled window fires a compute callback whose
future becomes the window's *sync function*; reusing a window slot (wrap
around) blocks on its previous sync — bounded memory over an unbounded stream
with natural backpressure.  On wrap, the trailing ``overlap`` bytes are
replicated to the buffer head so every window sees its carried-over context.

This is the framework's sequence-window component: for streaming/long-context
inference, window = sequence chunk and overlap = context carry-over (the
honest trtlab-equivalent slot for blockwise long-context; see SURVEY §2.8).
The TPU specialization over HBM buffers lives in
:mod:`tpulab.tpu.cyclic_buffer` (reference cuda/cyclic_windowed_buffer.h).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

from tpulab.memory.descriptor import Descriptor


class CyclicWindowedStack:
    """Cursor/sync state machine (reference cyclic_windowed_stack_impl:136-244).

    Subclasses (or users via ``on_window``) provide the per-window compute.
    ``append`` is single-producer; sync waits provide backpressure.
    """

    def __init__(self, buffer: Descriptor, window_count: int, window_size: int,
                 overlap: int = 0,
                 on_window: Optional[Callable[[int, memoryview], Optional[Future]]] = None):
        if overlap * 2 > window_size:
            # The slot-sync scheme is only sound when a window's carried-over
            # region fits inside one neighbor slot (overlap <= stride).
            raise ValueError("overlap must be <= window_size/2")
        if window_count < 2 and overlap:
            raise ValueError("overlap requires at least two windows")
        self.window_count = window_count
        self.window_size = window_size
        self.overlap = overlap
        self.stride = window_size - overlap
        required = window_count * self.stride + overlap
        if buffer.size < required:
            raise ValueError(f"buffer of {buffer.size} B too small; "
                             f"need {required} B for {window_count} windows")
        self._buffer = buffer
        self._view = buffer.memoryview()
        self._sync: List[Optional[Future]] = [None] * window_count
        self._cursor = 0          # absolute write offset in buffer
        self._win_id = 0          # global window counter
        self._on_window = on_window

    # -- geometry -----------------------------------------------------------
    def _slot(self, win_id: int) -> int:
        return win_id % self.window_count

    def _slot_offset(self, slot: int) -> int:
        return slot * self.stride

    @property
    def current_window(self) -> int:
        return self._win_id

    @property
    def bytes_in_current_window(self) -> int:
        return self._cursor - self._slot_offset(self._slot(self._win_id))

    # -- sync ---------------------------------------------------------------
    def _wait_slot(self, slot: int) -> None:
        fut = self._sync[slot]
        if fut is not None:
            fut.result()  # propagate compute errors; backpressure point
            self._sync[slot] = None

    def sync_all(self) -> None:
        """Wait for every in-flight window compute."""
        for slot in range(self.window_count):
            self._wait_slot(slot)

    # -- data path ----------------------------------------------------------
    def _write(self, offset: int, data: memoryview) -> None:
        """Host copy; the TPU specialization overrides with async device copy."""
        self._view[offset:offset + len(data)] = data

    def _replicate_overlap(self) -> None:
        """Copy buffer tail overlap to the head (wrap-around carry-over)."""
        end = self.window_count * self.stride + self.overlap
        self._write(0, self._view[end - self.overlap:end])

    def append(self, data) -> None:
        """Append bytes; fires window computes as windows fill. MAY BLOCK."""
        mv = memoryview(data).cast("B") if not isinstance(data, memoryview) else data.cast("B")
        pos = 0
        while pos < len(mv):
            slot = self._slot(self._win_id)
            win_end = self._slot_offset(slot) + self.window_size
            n = min(win_end - self._cursor, len(mv) - pos)
            self._wait_touched_slots(self._cursor, n)
            self._write(self._cursor, mv[pos:pos + n])
            self._cursor += n
            pos += n
            if self._cursor == win_end:
                self._complete_window()

    def _wait_touched_slots(self, offset: int, n: int) -> None:
        first = offset // self.stride
        last = min((offset + n - 1) // self.stride, self.window_count - 1)
        for s in range(first, last + 1):
            self._wait_slot(s)  # no-op when the slot's compute already landed

    def _complete_window(self) -> None:
        slot = self._slot(self._win_id)
        start = self._slot_offset(slot)
        window_view = self._view[start:start + self.window_size]
        if self._on_window is not None:
            fut = self._on_window(self._win_id, window_view)
            if fut is not None:
                self._sync[slot] = fut
        self._win_id += 1
        if self._slot(self._win_id) == 0:  # wrapped
            if self.overlap:
                self._wait_slot(0)
                self._replicate_overlap()
            self._cursor = self.overlap
        # else: cursor already sits `overlap` bytes into the next window

    def release(self) -> None:
        self.sync_all()
        self._view.release()
        self._buffer.release()


class CyclicWindowedTaskExecutor(CyclicWindowedStack):
    """Fires a compute task per filled window and records its future as the
    window's sync fn (reference cyclic_windowed_task_executor:369-440)."""

    def __init__(self, buffer: Descriptor, window_count: int, window_size: int,
                 overlap: int = 0,
                 compute_fn: Optional[Callable[[int, memoryview], object]] = None,
                 executor=None):
        super().__init__(buffer, window_count, window_size, overlap,
                         on_window=self._launch)
        self._compute_fn = compute_fn
        self._executor = executor  # ThreadPool-like with .enqueue

    def _launch(self, win_id: int, view: memoryview) -> Optional[Future]:
        if self._compute_fn is None:
            return None
        if self._executor is not None:
            return self._executor.enqueue(self._compute_fn, win_id, view)
        fut: Future = Future()
        try:
            fut.set_result(self._compute_fn(win_id, view))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)
        return fut


class CyclicWindowedReservedStack(CyclicWindowedStack):
    """Hands out one reserved window at a time for direct (zero-copy) filling
    (reference cyclic_windowed_reserved_stack:287-365)."""

    def __init__(self, buffer: Descriptor, window_count: int, window_size: int,
                 overlap: int = 0):
        super().__init__(buffer, window_count, window_size, overlap)
        self._reserved = False

    def reserve_window(self) -> Tuple[int, memoryview]:
        """Returns (window_id, writable view). Blocks if the slot is in flight."""
        if self._reserved:
            raise RuntimeError("a window is already reserved")
        slot = self._slot(self._win_id)
        self._wait_slot(slot)
        if self.overlap:
            # the window's tail extends `overlap` bytes into the next slot's
            # region — that slot's previous-cycle compute must have landed
            # before the caller writes through the view
            self._wait_slot((slot + 1) % self.window_count)
            if slot == 0 and self._win_id > 0:
                self._replicate_overlap()
        start = self._slot_offset(slot)
        self._reserved = True
        return self._win_id, self._view[start:start + self.window_size]

    def release_window(self, sync: Optional[Future] = None) -> None:
        """Mark the reserved window filled; ``sync`` is its compute future."""
        if not self._reserved:
            raise RuntimeError("no window reserved")
        slot = self._slot(self._win_id)
        if sync is not None:
            self._sync[slot] = sync
        self._win_id += 1
        self._cursor = self._slot_offset(self._slot(self._win_id)) + self.overlap
        self._reserved = False
