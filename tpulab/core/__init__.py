"""tpulab.core — host-side concurrency runtime (reference trtlab/core, ~10k LoC).

Components and their reference analogs:

- :mod:`threads` — thread-type policies (reference standard_threads.h /
  userspace_threads.h) and ``EventLoopGroup``, the Python-native analog of the
  boost.fiber ``FiberGroup`` (fiber_group.h:9-51): N OS threads each running an
  asyncio loop so handlers may *await* device/pool readiness without stalling
  any OS thread — the same property fibers give the reference.
- :mod:`pool` — blocking resource pools with RAII return-to-pool handles
  (reference pool.h v1-v4; v4 ``pop_shared``/``pop_unique`` semantics).
- :mod:`thread_pool` — work-queue pool with CPU-affinity constructors
  (reference thread_pool.h:73-298).
- :mod:`task_pool` — single-thread deadline scheduler for batching windows
  (reference task_pool.h:36-113).
- :mod:`batcher` / :mod:`dispatcher` — the dynamic batching state machine and
  its threaded/async execution wrappers (reference batcher.h, dispatcher.h).
- :mod:`affinity` — cpu_set algebra + NUMA topology (reference affinity.h/cc).
- :mod:`async_compute` — promise-fulfilling callable wrapper
  (reference async_compute.h:38-118).
- :mod:`cyclic_buffer` — sliding-window streaming compute over descriptors
  (reference cyclic_windowed_buffer.h:59-440).
- :mod:`dtypes` — DLPack-based dtype descriptors (reference types.h:40-139).
- :mod:`resources` — service-wide resource bundle base (reference resources.h).
"""

from tpulab.core.pool import Pool, UniquePool, Queue
from tpulab.core.thread_pool import ThreadPool
from tpulab.core.task_pool import DeferredShortTaskPool
from tpulab.core.batcher import StandardBatcher, Batch
from tpulab.core.dispatcher import Dispatcher, AsyncDispatcher
from tpulab.core.affinity import CpuSet, Affinity
from tpulab.core.async_compute import async_compute, SharedPackagedTask
from tpulab.core.threads import standard_threads, userspace_threads, EventLoopGroup
from tpulab.core.resources import Resources
from tpulab.core.dtypes import DType, dtype_from_numpy
from tpulab.core.cyclic_buffer import (
    CyclicWindowedStack,
    CyclicWindowedTaskExecutor,
    CyclicWindowedReservedStack,
)

__all__ = [
    "Pool", "UniquePool", "Queue",
    "ThreadPool", "DeferredShortTaskPool",
    "StandardBatcher", "Batch", "Dispatcher", "AsyncDispatcher",
    "CpuSet", "Affinity",
    "async_compute", "SharedPackagedTask",
    "standard_threads", "userspace_threads", "EventLoopGroup",
    "Resources", "DType", "dtype_from_numpy",
    "CyclicWindowedStack", "CyclicWindowedTaskExecutor",
    "CyclicWindowedReservedStack",
]
