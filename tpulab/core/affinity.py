"""CPU affinity + NUMA topology (reference affinity.h:36-109, affinity.cc).

``CpuSet`` is the set-algebra type (intersection/union/difference,
``from_string``); ``Affinity`` exposes per-thread get/set
(``os.sched_getaffinity``/``sched_setaffinity``), topology enumeration from
/sys, and a round-robin allocator.  ``AffinityGuard`` is the RAII scope.

On TPU hosts this is used to pin pre/post-processing threads and staging-buffer
first-touch to the NUMA node local to the TPU's PCIe root (the analog of the
reference's GPU<->CPU affinity from NVML, device_info.cc).
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Iterable, Iterator, List, Optional, Sequence


def _parse_cpulist(text: str) -> List[int]:
    """Parse kernel cpulist format: '0-3,8,10-11'."""
    cpus: List[int] = []
    text = text.strip()
    if not text:
        return cpus
    for part in text.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            cpus.extend(range(int(lo), int(hi) + 1))
        else:
            cpus.append(int(part))
    return cpus


class CpuSet:
    """Set of logical CPUs with set algebra (reference cpu_set)."""

    def __init__(self, cpus: Iterable[int] = ()):
        self._cpus = frozenset(int(c) for c in cpus)

    @classmethod
    def from_string(cls, s: str) -> "CpuSet":
        return cls(_parse_cpulist(s))

    def union(self, other: "CpuSet") -> "CpuSet":
        return CpuSet(self._cpus | other._cpus)

    def intersection(self, other: "CpuSet") -> "CpuSet":
        return CpuSet(self._cpus & other._cpus)

    def difference(self, other: "CpuSet") -> "CpuSet":
        return CpuSet(self._cpus - other._cpus)

    __or__ = union
    __and__ = intersection
    __sub__ = difference

    def __iter__(self) -> Iterator[int]:
        return iter(sorted(self._cpus))

    def __len__(self) -> int:
        return len(self._cpus)

    def __contains__(self, cpu: int) -> bool:
        return cpu in self._cpus

    def __eq__(self, other) -> bool:
        return isinstance(other, CpuSet) and self._cpus == other._cpus

    def __hash__(self) -> int:
        return hash(self._cpus)

    def __bool__(self) -> bool:
        return bool(self._cpus)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"CpuSet([{','.join(map(str, sorted(self._cpus)))}])"

    def get_nth(self, n: int) -> int:
        return sorted(self._cpus)[n]


class NumaNode:
    """One NUMA node: id + its CPUs (reference numa_node)."""

    def __init__(self, node_id: int, cpus: CpuSet):
        self.id = node_id
        self.cpus = cpus

    def __repr__(self) -> str:  # pragma: no cover
        return f"NumaNode({self.id}, {self.cpus!r})"


class Affinity:
    """Static topology + per-thread affinity API (reference affinity::*)."""

    _rr_lock = threading.Lock()
    _rr_next = 0

    # -- this_thread --------------------------------------------------------
    @staticmethod
    def get_affinity() -> CpuSet:
        return CpuSet(os.sched_getaffinity(0))

    @staticmethod
    def set_affinity(cpus: CpuSet | Sequence[int]) -> None:
        os.sched_setaffinity(0, set(cpus))

    # -- system topology ----------------------------------------------------
    @staticmethod
    def all_cpus() -> CpuSet:
        return CpuSet(range(os.cpu_count() or 1))

    @staticmethod
    def numa_nodes() -> List[NumaNode]:
        nodes = []
        for path in sorted(glob.glob("/sys/devices/system/node/node[0-9]*")):
            node_id = int(os.path.basename(path)[4:])
            try:
                with open(os.path.join(path, "cpulist")) as f:
                    cpus = CpuSet(_parse_cpulist(f.read()))
            except OSError:
                cpus = CpuSet()
            nodes.append(NumaNode(node_id, cpus))
        if not nodes:  # non-NUMA fallback: one node with everything
            nodes = [NumaNode(0, Affinity.all_cpus())]
        return nodes

    @classmethod
    def round_robin(cls, count: int, pool: Optional[CpuSet] = None) -> List[int]:
        """Allocate `count` CPUs round-robin from `pool` (reference allocator)."""
        cpus = sorted(pool or cls.all_cpus())
        out = []
        with cls._rr_lock:
            for _ in range(count):
                out.append(cpus[cls._rr_next % len(cpus)])
                cls._rr_next += 1
        return out


class AffinityGuard:
    """RAII affinity scope (reference affinity_guard)."""

    def __init__(self, cpus: CpuSet | Sequence[int]):
        self._saved = Affinity.get_affinity()
        Affinity.set_affinity(cpus)

    def __enter__(self) -> "AffinityGuard":
        return self

    def __exit__(self, *exc) -> None:
        Affinity.set_affinity(self._saved)
