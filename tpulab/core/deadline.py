"""End-to-end request deadlines (monotonic-clock budgets).

A client's deadline must survive every hop — router pick, RPC transport,
batcher queue, engine step — or slow replicas silently convert "answer in
200 ms" into "hold a lane for 300 s".  This module is the one shared
currency: a :class:`Deadline` wraps an absolute ``time.monotonic`` expiry
and every layer (ReplicaSet attempt budgets, the Generate RPC, the
continuous batcher's tick sweep, dense session streaming) checks the SAME
object semantics.  Cross-process propagation sends the *remaining budget*
(``GenerateRequest.deadline_ms``), never a wall-clock timestamp — replica
clocks need not agree.
"""

from __future__ import annotations

import time
from typing import Optional


class DeadlineExceeded(TimeoutError):
    """The request's end-to-end deadline expired.

    A ``TimeoutError`` subclass so generic timeout handling still works,
    but distinct so routers can tell "this request's global budget is
    spent — stop" from "this attempt stalled — fail over".
    """


class Deadline:
    """Absolute monotonic expiry; ``None`` seconds = no deadline.

    Cheap by design — one float — because a Deadline rides every request.
    """

    __slots__ = ("expiry",)

    def __init__(self, expiry: Optional[float]):
        self.expiry = expiry

    @classmethod
    def after(cls, seconds: Optional[float]) -> "Deadline":
        """Deadline ``seconds`` from now (``None`` -> unbounded)."""
        if seconds is None:
            return cls(None)
        return cls(time.monotonic() + max(0.0, float(seconds)))

    def remaining(self) -> Optional[float]:
        """Seconds left (clamped at 0), or None when unbounded."""
        if self.expiry is None:
            return None
        return max(0.0, self.expiry - time.monotonic())

    def expired(self) -> bool:
        return self.expiry is not None and time.monotonic() >= self.expiry

    def check(self, what: str = "request") -> None:
        if self.expired():
            raise DeadlineExceeded(f"{what} deadline exceeded")

    def per_attempt(self, attempts_left: int,
                    floor: float = 0.05) -> Optional[float]:
        """Budget for one of ``attempts_left`` remaining tries: an even
        split of what's left, floored so a nearly-spent deadline still
        issues a real attempt instead of a 0-second farce (the final
        expiry check, not the floor, is what enforces the deadline)."""
        rem = self.remaining()
        if rem is None:
            return None
        return max(floor, rem / max(1, attempts_left))

    def bound(self, timeout: Optional[float]) -> Optional[float]:
        """``min(timeout, remaining)`` treating None as unbounded."""
        rem = self.remaining()
        if rem is None:
            return timeout
        if timeout is None:
            return rem
        return min(timeout, rem)

    def __repr__(self) -> str:
        rem = self.remaining()
        return ("Deadline(unbounded)" if rem is None
                else f"Deadline(remaining={rem:.3f}s)")
