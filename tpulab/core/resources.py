"""Service-wide resource bundle base (reference resources.h:33-42).

RPC contexts downcast the shared Resources object to their concrete type via
``cast()`` — the Python analog of ``casted_shared_from_this<T>()``.
"""

from __future__ import annotations

from typing import Type, TypeVar

T = TypeVar("T", bound="Resources")


class Resources:
    """Base class for bundles of pools/clients/managers shared by services."""

    def cast(self, cls: Type[T]) -> T:
        if not isinstance(self, cls):
            raise TypeError(f"resources are {type(self).__name__}, not {cls.__name__}")
        return self
