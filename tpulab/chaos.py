"""Deterministic fault injection (the resilience analog of the reference's
nvrpc test doubles, extended to a serving stack that must *prove* graceful
degradation: SURVEY §2.4 health/drain machinery, PAPERS.md adaptive-
orchestration resilience argument).

TPU-native serving fails in ways trtlab's single-host GPU story never
exercised — preempted TPU VMs, multihost meshes losing a worker, streaming
LLM requests holding lanes for seconds — so the failover/deadline/breaker
paths need to be drivable *on demand and reproducibly*.  This module plants
named **injection points** on the hot paths; each is a single
``chaos.trip("<point>")`` call that costs ONE module-global ``is None``
branch when disarmed (no threads, no locks, no allocation — production pays
nothing).

Armed, a :class:`FaultSchedule` maps points to rules:

    with chaos.inject(FaultSchedule.parse(
            "engine.step=delay:0.02;rpc.client.unary=error@2", seed=7)):
        ...

or via environment (picked up at import, so subprocess replicas arm
themselves)::

    TPULAB_CHAOS="rpc.server.generate_token=kill@3" python server.py

Rule grammar (``;``-separated)::

    <point>=<action>[:<value>][@<after>][+<times>][%<prob>]

    action  error  raise ChaosError at the point (transient fault)
            delay  sleep <value> seconds (slow step / slow link)
            drop   black-hole the operation (only points that declare
                   drop support honor it; others treat it as error)
            kill   os._exit(86) — replica process death (use only on
                   subprocess replicas!)
    @N      skip the first N occurrences of the point (default 0)
    +K      fire at most K times (default unlimited)
    %P      fire with probability P per eligible occurrence, drawn from
            the schedule's seeded RNG (default 1.0 — deterministic)

Occurrence counting is per-point and process-global; with the default
``%1.0`` a schedule is fully deterministic, and with ``%P`` the seeded RNG
makes the *sequence of draws* reproducible.

Injection points currently planted (see docs/ROBUSTNESS.md):

    rpc.client.unary          ClientUnary.start, before the call (drop-capable)
    rpc.client.stream_recv    ClientStreaming read loop, per response
    rpc.server.generate_token GenerateContext dense loop, per token (kill site)
    rpc.stream                GenerateContext token-EMIT site, per token
                              (dense AND paged paths) — error kills the
                              stream mid-flight with a retryable INTERNAL
                              (clients fail over, resuming from delivered);
                              delay slows the emit; drop latches the stream
                              STALLED: it stops emitting but stays open
                              with no final, exactly what the inter-token
                              stall watchdog exists to catch
    serving.admission         AdmissionController.admit — error/drop force a
                              RESOURCE_EXHAUSTED rejection (synthetic
                              overload), delay models a slow decision
    engine.step               ContinuousBatcher tick + GenerationSession.step
    engine.prefill            ContinuousBatcher fused prefill
    engine.verify             ContinuousBatcher speculative verify dispatch
                              (once per speculative dispatch, BEFORE it is
                              issued) — error/drop degrade the dispatch's
                              lanes to plain decode blocks for the rest of
                              each request: nothing was emitted yet, so
                              never a corrupt or duplicated token
    device.transfer           Bindings.copy_to_device (host->device staging)
    kvcache.swap              KVOffloadManager swap-out/restore/demote/
                              promote — error/drop degrade that swap to the
                              pre-offload recompute path (the lane/entry is
                              never corrupted, work is just recomputed)
    disagg.ship               KVShipper export/import (tpulab.disagg) —
                              error/drop lose that KV shipment: the decode
                              replica degrades to a local prefill, never a
                              corrupt lane or a stuck request
    fabric.pull               fleet KV fabric (tpulab.kvfabric), tripped on
                              BOTH sides of a cross-replica prefix fetch —
                              owner-side export (error/drop make the owner
                              answer an honest NOT_FOUND) and fetcher-side
                              pull (error/drop abandon the fetch): either
                              way the request degrades to a local prefill,
                              never a corrupt or partial adoption
    modelstore.swap           WeightMultiplexer swap-out/swap-in
                              (tpulab.modelstore) — error/drop at swap-out
                              lose that model's weight snapshot (HBM still
                              frees; the next acquire cold-rebuilds), at
                              swap-in discard the host copy and serve a
                              cold rebuild instead: degraded weights are
                              always REBUILT weights, never a corrupt serve
    fleet.route               GenerationReplicaSet._pick_affine, the head
                              of the prefix-affinity routing decision
                              (tpulab.fleet) — error fails that decision
                              and the pick degrades to the existing
                              load-based selection; drop disables
                              affinity for that request (same fallback,
                              distinct evidence): routing chaos can only
                              forgo cache warmth, never strand a request
    fleet.spawn               spawn_with_retry (tpulab.fleet.autoscaler),
                              once per provider spawn attempt — error
                              fails the attempt, drop models a spawn the
                              scheduler lost (never came up); both
                              degrade to bounded retry-with-backoff, so
                              spawn chaos can delay capacity, never
                              wedge the autoscaler or the supervisor
    fleet.probe               FleetSupervisor.probe (tpulab.fleet), once
                              per member classification — error/drop
                              forgo THAT member's probe this tick
                              (evidence discarded, retried next tick):
                              probe chaos can delay healing, never
                              declare a healthy replica dead
    batch.run                 BatchScheduler run loop (tpulab.batch), once
                              per scheduler pass — error/drop kill the
                              batch RUNNER mid-job: in-flight items are
                              cancelled (their lanes free at the next tick),
                              delivered tokens stay durable in the JSONL
                              checkpoint sink, and the next run() resumes
                              from delivered tokens with zero re-decode —
                              batch chaos can cost idle-capacity soak,
                              never online traffic or delivered work
    hbm.pressure              HBMArbiter decision sites (tpulab.hbm): one
                              trip per pressed tenant per pressure round
                              (demote-KV, evict-model) and one at the
                              denial — error/drop suppress that decision,
                              so the requester degrades to its pre-arbiter
                              static-budget behavior (the mux waits on its
                              own budget, the batcher queues on its current
                              pool).  The ledger is never touched on a
                              tripped path: chaos can forgo the
                              optimization, never corrupt the accounting
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger("tpulab.chaos")

#: module-global armed schedule; ``None`` (the default) is the ONE branch
#: every injection point pays in production
_ARMED: Optional["FaultSchedule"] = None

#: optional fire observer ``fn(point, action)`` — the metrics bridge
#: (tpulab.utils.metrics.ChaosMetrics).  Called ONLY when a rule actually
#: fires, outside the schedule lock, before the action executes (so even a
#: ``kill`` is counted on its way out); never on the disarmed path.
_OBSERVER = None

_ACTIONS = ("error", "delay", "drop", "kill")

#: exit code for the ``kill`` action — distinguishable from a real crash
KILL_EXIT_CODE = 86


class ChaosError(RuntimeError):
    """The injected transient fault (``error`` action).  A RuntimeError on
    purpose: callers must survive it through their *generic* failure
    handling, not a chaos-aware special case."""


class FaultRule:
    """One point's behavior: action + occurrence window + probability."""

    __slots__ = ("point", "action", "value", "after", "times", "prob")

    def __init__(self, point: str, action: str, value: float = 0.0,
                 after: int = 0, times: Optional[int] = None,
                 prob: float = 1.0):
        if action not in _ACTIONS:
            raise ValueError(f"unknown chaos action {action!r} "
                             f"(want one of {_ACTIONS})")
        if not 0.0 <= prob <= 1.0:
            raise ValueError("prob must be in [0, 1]")
        self.point = point
        self.action = action
        self.value = float(value)
        self.after = int(after)
        self.times = times
        self.prob = float(prob)

    @classmethod
    def parse(cls, spec: str) -> "FaultRule":
        """``point=action[:value][@after][+times][%prob]`` (module grammar)."""
        point, _, rhs = spec.partition("=")
        if not rhs:
            raise ValueError(f"chaos rule {spec!r}: want point=action[...]")
        kw = dict(value=0.0, after=0, times=None, prob=1.0)
        # peel modifiers right-to-left; each marker appears at most once
        for marker, key, conv in (("%", "prob", float), ("+", "times", int),
                                  ("@", "after", int)):
            if marker in rhs:
                rhs, _, raw = rhs.rpartition(marker)
                kw[key] = conv(raw)
        action, _, val = rhs.partition(":")
        if val:
            kw["value"] = float(val)
        return cls(point.strip(), action.strip(), **kw)

    def __repr__(self) -> str:
        return (f"FaultRule({self.point}={self.action}:{self.value}"
                f"@{self.after}+{self.times}%{self.prob})")


class FaultSchedule:
    """Seeded, deterministic rule set driving the injection points.

    Thread-safe: occurrence counters and the RNG sit behind one lock (the
    armed path is the *test* path — production never reaches it)."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._seen: Dict[str, int] = {}    # point -> occurrences observed
        self._fired: Dict[str, int] = {}   # point -> rule activations
        self._per_rule_fired = [0] * len(self.rules)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultSchedule":
        rules = [FaultRule.parse(part)
                 for part in spec.split(";") if part.strip()]
        return cls(rules, seed=seed)

    # -- observability (test assertions) ------------------------------------
    def occurrences(self, point: str) -> int:
        """How many times ``point`` was reached (armed window only)."""
        with self._lock:
            return self._seen.get(point, 0)

    def fired(self, point: str) -> int:
        """How many times a rule activated at ``point``."""
        with self._lock:
            return self._fired.get(point, 0)

    def fired_snapshot(self) -> Dict[str, int]:
        """Copy of every point's activation count — diffed around a
        request window by the flight recorder (tpulab.obs) to attribute
        "a chaos rule fired while this request was in flight"."""
        with self._lock:
            return dict(self._fired)

    def seen_snapshot(self) -> Dict[str, int]:
        """Copy of every point's occurrence count (the debugz view)."""
        with self._lock:
            return dict(self._seen)

    # -- the injection-point entry ------------------------------------------
    def fire(self, point: str) -> Optional[str]:
        """Apply the first matching eligible rule.  Returns ``"drop"`` when
        a drop rule fires (the call site black-holes the operation), raises
        :class:`ChaosError` for ``error``, sleeps for ``delay``, exits the
        process for ``kill``; returns None when nothing fires."""
        action = None
        value = 0.0
        with self._lock:
            n = self._seen.get(point, 0)
            self._seen[point] = n + 1
            for i, rule in enumerate(self.rules):
                if rule.point != point or n < rule.after:
                    continue
                if (rule.times is not None
                        and self._per_rule_fired[i] >= rule.times):
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                self._per_rule_fired[i] += 1
                self._fired[point] = self._fired.get(point, 0) + 1
                action, value = rule.action, rule.value
                break
        if action is None:
            return None
        obs = _OBSERVER
        if obs is not None:
            try:
                obs(point, action)
            except Exception:  # pragma: no cover - observer must not
                pass           # change injection behavior
        log.debug("chaos: %s at %s (value=%s)", action, point, value)
        if action == "delay":
            if value > 0:
                time.sleep(value)
            return None
        if action == "error":
            raise ChaosError(f"injected fault at {point}")
        if action == "kill":
            # a replica process death, not an exception: no finally blocks,
            # no grpc goodbye — the peer sees a TCP reset
            os._exit(KILL_EXIT_CODE)
        return "drop"


def trip(point: str) -> Optional[str]:
    """THE injection point.  Disarmed cost: one global load + one branch.
    Returns ``"drop"`` when an armed drop rule fires (only call sites that
    can black-hole an operation need to look at the return value)."""
    s = _ARMED
    if s is None:
        return None
    return s.fire(point)


def arm(schedule: Optional[FaultSchedule]) -> None:
    """Install (or with ``None`` remove) the process-wide schedule."""
    global _ARMED
    _ARMED = schedule


def armed() -> Optional[FaultSchedule]:
    return _ARMED


def fired_snapshot() -> Dict[str, int]:
    """Per-point activation counts of the armed schedule ({} disarmed) —
    the window-diff source for per-request chaos attribution."""
    s = _ARMED
    return {} if s is None else s.fired_snapshot()


def set_observer(fn) -> None:
    """Install (or with ``None`` remove) the process-wide fire observer
    ``fn(point, action)``.  One slot, cold path: tests/telemetry install a
    ChaosMetrics bridge so fault-injection experiments are self-measuring."""
    global _OBSERVER
    _OBSERVER = fn


class inject:
    """Context manager arming a schedule for a ``with`` block::

        sched = FaultSchedule.parse("engine.step=error+1", seed=3)
        with chaos.inject(sched):
            ...

    Accepts a :class:`FaultSchedule` or a spec string.  Re-entrant use
    restores the previously armed schedule on exit (nesting composes the
    way tests expect: innermost wins)."""

    def __init__(self, schedule, seed: int = 0):
        if isinstance(schedule, str):
            schedule = FaultSchedule.parse(schedule, seed=seed)
        self.schedule = schedule
        self._prev: Optional[FaultSchedule] = None

    def __enter__(self) -> FaultSchedule:
        self._prev = _ARMED
        arm(self.schedule)
        return self.schedule

    def __exit__(self, *exc) -> None:
        arm(self._prev)


def _arm_from_env() -> None:
    """``TPULAB_CHAOS`` arms at import so subprocess replicas inherit the
    schedule through their environment (``TPULAB_CHAOS_SEED`` seeds it)."""
    spec = os.environ.get("TPULAB_CHAOS", "").strip()
    if not spec:
        return
    seed = int(os.environ.get("TPULAB_CHAOS_SEED", "0"))
    arm(FaultSchedule.parse(spec, seed=seed))
    log.warning("chaos armed from TPULAB_CHAOS=%r (seed=%d)", spec, seed)


_arm_from_env()
