"""HBM arbiter: the pressure protocol between device-memory tenants.

The :class:`~tpulab.hbm.ledger.DeviceHBMLedger` says who holds what; the
:class:`HBMArbiter` decides who gets the NEXT byte.  Tenants register
with up to three hooks:

- ``reclaim(nbytes) -> int`` — asked to free ``nbytes`` of device
  memory.  The KV tenant demotes live-but-idle KV to the host tier and
  shrinks its elastic page pool (the batcher services the request at its
  next tick boundary); the weights tenant initiates write-behind
  swap-outs of cold unleased models.  Returns the bytes the tenant
  *expects* to free (0 = nothing reclaimable right now); actual ledger
  releases land asynchronously and wake the arbiter.
- ``reclaimable() -> int`` — non-mutating estimate of what ``reclaim``
  could free, for the admission frontend's honest headroom number.
- ``gauge() -> int`` — the tenant's live tracked device bytes, for
  :meth:`verify` (the ledger-vs-allocator invariant the tests enforce).

:meth:`request` is the only way bytes are GRANTED: it atomically claims
from ledger headroom when available, otherwise runs pressure rounds —
each round asks every *other* tenant to reclaim the deficit, then waits
for write-behind releases to land.  Rounds where no tenant can help are
counted; two barren rounds (or the timeout) end in a **denial** and the
requester degrades to its pre-arbiter static-budget behavior — the
no-livelock guarantee when every tenant is at budget.

Chaos (``hbm.pressure``, docs/ROBUSTNESS.md): the trip point guards
every decision site — pressing the KV tenant (demote-KV), pressing the
weights tenant (evict-model), and the denial itself.  ``error``/``drop``
suppress that decision: the pressure simply does not happen and the
requester falls back to static-budget behavior.  The ledger is never
touched on a tripped path, so a chaos storm can never corrupt the
accounting — only forgo optimization.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Dict, Hashable, List, Optional

from tpulab import chaos
from tpulab.hbm.ledger import DeviceHBMLedger

__all__ = ["HBMArbiter", "KV_TENANT", "WEIGHTS_TENANT", "SCRATCH_TENANT",
           "benchmark_hbm_arbiter"]

#: canonical tenant names (the ledger key's first half); the 2D-mesh
#: work extends tags, not these
KV_TENANT = "kv"
WEIGHTS_TENANT = "weights"
SCRATCH_TENANT = "scratch"


class _Tenant:
    __slots__ = ("name", "reclaim", "reclaimable", "gauge")

    def __init__(self, name: str, reclaim=None, reclaimable=None,
                 gauge=None):
        self.name = name
        self.reclaim = reclaim
        self.reclaimable = reclaimable
        self.gauge = gauge


class HBMArbiter:
    """One device's HBM economy (module docstring).

    ``capacity_bytes`` is the budget every tenant together rents within.
    ``measure_scratch`` arms compile-time scratch claims
    (:class:`~tpulab.hbm.scratch.MeasuredJit`); tests that need a tight
    deterministic budget turn it off.  ``metrics`` is an optional
    :class:`~tpulab.utils.metrics.HBMMetrics`."""

    #: default bound on how long a blocking request runs pressure rounds
    REQUEST_TIMEOUT_S = 10.0
    #: per-round wait for write-behind reclaims to land
    PRESSURE_POLL_S = 0.02
    #: consecutive rounds with nothing reclaimable before an early denial
    #: (the no-livelock guard: both-tenants-at-budget resolves in two
    #: rounds, not at the timeout)
    BARREN_ROUNDS = 2
    #: how long a round's reclaim promise is trusted to be in flight —
    #: no re-press while promised bytes may still be landing (prevents
    #: over-reclaim: a squeezed pool must lose the deficit, not double it)
    PROMISE_GRACE_S = 0.5

    def __init__(self, capacity_bytes: int, metrics=None,
                 measure_scratch: bool = True):
        self.ledger = DeviceHBMLedger(capacity_bytes)
        self.measure_scratch = bool(measure_scratch)
        self.metrics = metrics
        self._tenants: Dict[str, _Tenant] = {}
        self._reg_lock = threading.Lock()
        #: outstanding blocking requests (id -> (tenant, nbytes)): bytes
        #: freed under pressure are RESERVED for the waiters — another
        #: tenant's claim cannot steal them back mid-squeeze (without
        #: this, the squeezed tenant's own refill request wins the race
        #: for its just-reclaimed bytes and the presser starves)
        self._waiting: Dict[int, tuple] = {}
        self._wait_seq = 0
        # -- counters (HBMMetrics.poll advances from these) ------------------
        self.grants = 0           # requests satisfied (with or without
        #                           pressure)
        self.pressure_events = 0  # pressure rounds run
        self.demotions_forced = 0   # rounds where the KV tenant reclaimed
        self.evictions_forced = 0   # rounds where the weights tenant did
        self.denials = 0          # requests denied (timeout / barren)
        self.reclaims_by_tenant: Dict[str, int] = {}

    # -- registration --------------------------------------------------------
    def register(self, name: str,
                 reclaim: Optional[Callable[[int], int]] = None,
                 reclaimable: Optional[Callable[[], int]] = None,
                 gauge: Optional[Callable[[], int]] = None) -> None:
        with self._reg_lock:
            if name in self._tenants:
                raise ValueError(f"HBM tenant {name!r} already registered")
            self._tenants[name] = _Tenant(name, reclaim, reclaimable, gauge)

    def _tenant_list(self) -> List[_Tenant]:
        with self._reg_lock:
            return list(self._tenants.values())

    # -- ledger mirrors ------------------------------------------------------
    # These record what a tenant's byte-accurate accounting already holds
    # (registration of existing residency, elastic-pool resizes, static-
    # fallback acquisitions).  They are bookkeeping, not grants — the
    # ledger stays exact even when a tenant proceeds on its static path,
    # which is why verify() holds on every degraded branch.
    def claim(self, tenant: str, tag: Hashable, nbytes: int) -> None:
        self.ledger.claim(tenant, tag, nbytes)

    def mirror_claim(self, tenant: str, tag: Hashable, nbytes: int) -> None:
        self.ledger.resize(tenant, tag, nbytes)

    def release(self, tenant: str, tag: Hashable) -> int:
        return self.ledger.release(tenant, tag)

    def record_scratch(self, tag: Hashable, nbytes: int) -> None:
        """Per-jit compile-time scratch claim (tpulab.hbm.scratch)."""
        if self.measure_scratch:
            self.ledger.resize(SCRATCH_TENANT, tag, nbytes)

    # -- views ---------------------------------------------------------------
    @property
    def capacity_bytes(self) -> int:
        return self.ledger.capacity_bytes

    @property
    def free_hbm_bytes(self) -> int:
        """THE headroom number (Status RPC gauge, admission's honest
        input): capacity minus every tenant's claims — weights, KV pages
        and compiled scratch together, never two optimistic per-tenant
        estimates."""
        return self.ledger.headroom_bytes

    def reclaimable_bytes(self, exclude: Optional[str] = None) -> int:
        """Bytes the OTHER tenants estimate pressure could free right now
        (admission counts this next to free headroom — demotable KV and
        evictable cold models are capacity, just not free capacity)."""
        total = 0
        for t in self._tenant_list():
            if t.name == exclude or t.reclaimable is None:
                continue
            try:
                total += max(0, int(t.reclaimable()))
            except Exception:  # a torn-down tenant must not wedge callers
                pass
        return total

    def gauges(self) -> Dict[str, int]:
        """Live tracked device bytes per tenant that registered a gauge."""
        out: Dict[str, int] = {}
        for t in self._tenant_list():
            if t.gauge is not None:
                try:
                    out[t.name] = int(t.gauge())
                except Exception:
                    pass
        return out

    def reservations(self) -> List[Dict[str, Any]]:
        """Live anti-steal reservations: bytes freed under pressure that
        are being held for blocked requesters (the debugz view of
        ``_waiting`` — empty in steady state)."""
        with self.ledger._cv:
            return [{"tenant": t, "bytes": int(n)}
                    for t, n in self._waiting.values()]

    def verify(self) -> Dict[str, Any]:
        """Ledger-vs-gauges cross-check (empty dict = consistent)."""
        return self.ledger.verify(self.gauges())

    # -- the decision --------------------------------------------------------
    def request(self, tenant: str, tag: Hashable, nbytes: int,
                timeout: Optional[float] = None,
                probe: bool = False) -> bool:
        """Grant ``nbytes`` to ``(tenant, tag)`` — atomically claimed in
        the ledger on success.  When headroom is short, pressure rounds
        ask the other tenants to reclaim the deficit (demote-KV /
        evict-model, each a chaos decision site) and wait for the
        releases to land.  ``probe=True`` runs at most one pressure
        round and returns immediately without counting a denial — the
        batcher's per-tick grow probe, cheap enough to retry every tick.

        False = denied: the requester must degrade to its pre-arbiter
        static-budget behavior (the mux waits on its own budget, the
        batcher queues on its current pool)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        end = _time.monotonic() + (self.REQUEST_TIMEOUT_S
                                   if timeout is None else max(0.0, timeout))
        barren = 0
        expected_headroom = None  # promised bytes still landing
        promise_end = 0.0
        wid = None
        try:
            while True:
                if self._try_claim(tenant, tag, nbytes, wid):
                    self.grants += 1
                    return True
                if wid is None and not probe:
                    # entering the pressure path: reserve the bytes this
                    # request is squeezing for — no other tenant's claim
                    # may take them while the reclaim lands
                    led = self.ledger
                    with led._cv:
                        self._wait_seq += 1
                        wid = self._wait_seq
                        self._waiting[wid] = (tenant, nbytes)
                headroom = self.ledger.headroom_bytes
                now = _time.monotonic()
                if (expected_headroom is not None
                        and headroom < expected_headroom
                        and now < promise_end):
                    # a prior round's reclaim is still landing (write-
                    # behind copies, the batcher's tick-boundary
                    # service): wait it out instead of pressing again —
                    # re-pressing would make tenants over-reclaim
                    # (shrink twice for one deficit)
                    self.ledger.wait_for_change(
                        min(self.PRESSURE_POLL_S, max(0.001, end - now)))
                    continue
                deficit = nbytes - headroom
                initiated = self._pressure_round(tenant, deficit)
                if initiated:
                    expected_headroom = headroom + initiated
                    promise_end = now + self.PROMISE_GRACE_S
                if probe:
                    if initiated and self._try_claim(tenant, tag, nbytes,
                                                     wid):
                        self.grants += 1
                        return True
                    return False  # probes retry next tick; not a denial
                barren = 0 if initiated else barren + 1
                now = _time.monotonic()
                if barren >= self.BARREN_ROUNDS or now >= end:
                    return self._deny(tenant, nbytes)
                self.ledger.wait_for_change(
                    min(self.PRESSURE_POLL_S, max(0.001, end - now)))
        finally:
            if wid is not None:
                led = self.ledger
                with led._cv:
                    self._waiting.pop(wid, None)
                    led._cv.notify_all()

    def _try_claim(self, tenant: str, tag: Hashable, nbytes: int,
                   wid=None) -> bool:
        led = self.ledger
        with led._cv:
            key = (tenant, tag)
            have = led._claims.get(key, 0)
            # bytes reserved for OTHER waiting requesters are off-limits
            # (a waiter's own reservation never blocks its own claim)
            reserved = sum(n for w, (t, n) in self._waiting.items()
                           if t != tenant and w != wid)
            if (led.capacity_bytes - sum(led._claims.values()) - reserved
                    >= nbytes - have):
                led._claims[key] = have + nbytes
                led._cv.notify_all()
                return True
            return False

    def _pressure_round(self, requester: str, deficit: int) -> int:
        """One round of cross-tenant pressure.  Returns the bytes the
        pressed tenants expect to free (0 = barren round).  Each press is
        a chaos decision site: error/drop suppress that press — the
        degrade is a skipped optimization, never a ledger mutation."""
        self.pressure_events += 1
        initiated = 0
        for t in self._tenant_list():
            if t.name == requester or t.reclaim is None:
                continue
            try:
                if chaos.trip("hbm.pressure") == "drop":
                    continue  # pressure black-holed: static degrade
            except chaos.ChaosError:
                continue      # injected fault: same degrade, never corrupt
            try:
                got = max(0, int(t.reclaim(int(deficit)) or 0))
            except Exception:  # a broken tenant must not wedge requests
                got = 0
            if got > 0:
                initiated += got
                self.reclaims_by_tenant[t.name] = (
                    self.reclaims_by_tenant.get(t.name, 0) + 1)
                if t.name == KV_TENANT:
                    self.demotions_forced += 1
                elif t.name == WEIGHTS_TENANT:
                    self.evictions_forced += 1
        return initiated

    def _deny(self, tenant: str, nbytes: int) -> bool:
        try:
            chaos.trip("hbm.pressure")  # the deny decision site
        except chaos.ChaosError:
            pass  # an injected fault at deny still denies, atomically
        self.denials += 1
        return False


# -- the bench row ------------------------------------------------------------
def benchmark_hbm_arbiter(lanes: int = 4, steps: int = 24,
                          prompt_len: int = 8, page_size: int = 8,
                          d_model: int = 256, n_heads: int = 4,
                          n_layers: int = 4, vocab: int = 256,
                          n_llm: int = 12,
                          dtype=None) -> Dict[str, Any]:
    """The bench ``hbm_arbiter`` row: a mixed model-swap + KV-burst trace
    under device-HBM oversubscription, arbiter ON vs today's static
    split.

    One device budget holds EITHER the full KV burst's pages OR the
    second model's weights — never both.  The trace interleaves an
    ``n_llm``-request LLM burst through the paged batcher with forwards
    on a second dense model:

    - **static split** (the pre-arbiter baseline): the pool is fixed at
      its small static share and the second model owns its own weight
      budget — the burst grinds through a starved pool while the model's
      bytes sit idle between forwards;
    - **arbiter on**: the burst grows the pool by evicting the cold
      model (write-behind swap-out), and the model's next acquire
      presses the KV tenant back down (demote + shrink) — the same bytes
      serve whichever side is under load.

    Both modes must produce identical greedy tokens and model outputs
    (``parity``); the headline is goodput (completed ops/s) plus the
    arbiter's demotion/eviction/denial counters."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpulab.engine.paged import ContinuousBatcher
    from tpulab.models.transformer import init_transformer_params

    dtype = dtype or jnp.float32
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (prompt_len,), np.int32)
               for _ in range(n_llm)]

    max_len = prompt_len + steps + 2
    pages_per_req = (max_len + page_size - 1) // page_size
    full_pages = lanes * pages_per_req + 1      # the burst's working set
    small_pages = pages_per_req + 1             # the static KV share
    # the batcher's elastic pool snaps to its size ladder (small * 2^k),
    # so the burst's reachable top is the first ladder rung >= full
    top_pages = small_pages
    while top_pages < full_pages:
        top_pages *= 2
    page_nbytes = (n_layers * 2 * page_size * n_heads
                   * (d_model // n_heads) * np.dtype(np.float32).itemsize)
    # model B sized at exactly the pool's elastic range: holding B hot
    # and serving the full burst are mutually exclusive under ``capacity``
    # (top rung + half a page of slack — never the full pool AND B)
    b_words = (top_pages - small_pages) * page_nbytes // 4
    capacity = top_pages * page_nbytes + page_nbytes // 2

    params = init_transformer_params(vocab=vocab, d_model=d_model,
                                     n_heads=n_heads, n_layers=n_layers,
                                     d_ff=4 * d_model)

    def build_model_b():
        r = np.random.default_rng(7)
        return {"w": jnp.asarray(
            r.standard_normal((b_words,)).astype(np.float32))}

    b_fwd = jax.jit(lambda p: jnp.tanh(p["w"][:256]).sum())

    class _Servable:
        def __init__(self):
            self.device_params = jax.device_put(build_model_b())

        def resident(self):
            return self.device_params is not None

        def param_bytes(self):
            from tpulab.modelstore.host_store import tree_nbytes
            return tree_nbytes(self.device_params or build_model_b())

        def busy(self):
            return False

        def detach(self):
            dev, self.device_params = self.device_params, None
            return dev

        def on_detached(self):
            pass

        def attach(self, host_tree):
            self.device_params = jax.device_put(host_tree)

        def rebuild(self):
            return build_model_b()

    def run(arbiter_on: bool) -> Dict[str, Any]:
        from tpulab.modelstore import WeightMultiplexer

        b = _Servable()
        b_bytes = b.param_bytes()
        arb = (HBMArbiter(capacity, measure_scratch=False)
               if arbiter_on else None)
        # the static split can only run the lanes its fixed pool carries
        # (a pre-arbiter deployment sizes lanes to the pool — admitting
        # more would page-hoard-deadlock); the arbiter mode runs the full
        # lane count because the pool grows to meet the burst
        run_lanes = lanes if arbiter_on else max(
            1, (small_pages - 1) // pages_per_req)
        cb = ContinuousBatcher(
            params, n_heads=n_heads, n_layers=n_layers, lanes=run_lanes,
            max_len=max_len, page_size=page_size, n_pages=small_pages,
            compute_dtype=dtype, kv_offload=True, hbm=arb)
        mux = WeightMultiplexer(max(b_bytes, 1), hbm=arb)
        mux.register("b", _BenchAdapter(b))

        tokens: List[List[int]] = []
        outs: List[float] = []

        def b_op():
            lease = mux.acquire("b")
            try:
                outs.append(round(float(np.asarray(
                    b_fwd(b.device_params))), 4))
            finally:
                lease.release()

        # warm the compiles out of the measurement (the kv_offload-row
        # discipline).  Two waves: the first grows the pool mid-burst
        # (arbiter mode), the second prefills + decodes entirely at the
        # grown shape — every (program, pool-shape) pair the measured
        # trace hits is compiled here; the b_op warms the squeeze path
        for _ in range(2):
            for f in [cb.submit(p, steps) for p in prompts[:lanes]]:
                f.result(timeout=300)
        b_op()
        outs.clear()
        d0 = dict(denials=arb.denials, demotions=arb.demotions_forced,
                  evictions=arb.evictions_forced) if arb else {}
        pre0, grow0, shrink0 = cb.preemptions, cb.hbm_grows, cb.hbm_shrinks
        t0 = _time.perf_counter()
        b_op()  # model op before the burst: B hot, pool squeezed
        futs = [cb.submit(p, steps) for p in prompts]
        # a model op lands mid-burst: the arbiter must squeeze KV back
        futs[0].result(timeout=300)
        b_op()
        for f in futs:
            tokens.append([int(t) for t in f.result(timeout=300)])
        b_op()  # and one after: swap back in (bit-exact either way)
        wall = max(1e-6, _time.perf_counter() - t0)
        out = {
            "wall_s": round(wall, 3),
            "goodput_ops_s": round((len(futs) + 3) / wall, 2),
            "tokens": tokens, "model_outs": outs,
            "pool_pages_final": cb.pool.n_pages,
            "preemptions": cb.preemptions - pre0,
        }
        if arb is not None:
            out.update(
                demotions=arb.demotions_forced - d0["demotions"],
                evictions=arb.evictions_forced - d0["evictions"],
                denials=arb.denials - d0["denials"],
                grows=cb.hbm_grows - grow0,
                shrinks=cb.hbm_shrinks - shrink0,
                free_hbm_mb=round(arb.free_hbm_bytes / 2**20, 3))
        cb.shutdown()
        mux.close()
        return out

    on, off = run(True), run(False)
    parity = (on.pop("tokens") == off.pop("tokens")
              and on.pop("model_outs") == off.pop("model_outs"))
    return {
        "lanes": lanes, "steps": steps, "n_llm": n_llm,
        "small_pages": small_pages, "full_pages": full_pages,
        "arbiter_on": on, "static_split": off,
        "parity": parity,
        "goodput_ratio": round(
            on["goodput_ops_s"] / max(1e-9, off["goodput_ops_s"]), 3),
    }


class _BenchAdapter:
    """Adapter façade over the bench servable (same protocol as
    CompiledModelAdapter/BatcherAdapter)."""

    def __init__(self, servable):
        self._s = servable

    def resident(self):
        return self._s.resident()

    def param_bytes(self):
        return self._s.param_bytes()

    def busy(self):
        return self._s.busy()

    def detach(self):
        return self._s.detach()

    def on_detached(self):
        self._s.on_detached()

    def attach(self, host_tree):
        self._s.attach(host_tree)

    def rebuild(self):
        return self._s.rebuild()
