"""Byte-accurate device-HBM ledger (the accounting half of tpulab.hbm).

One ledger per device (or per logical device set under a mesh) records
every byte a tenant holds in HBM as a ``(tenant, tag)`` claim:

- the KV page pool claims its page store under ``("kv", "pool")`` and
  resizes the claim when the elastic pool grows/shrinks;
- the weight multiplexer claims each hot model under
  ``("weights", model_name)`` for exactly as long as its own byte
  accounting holds the bytes (a write-behind swap-out releases the claim
  when the host copy LANDS, mirroring ``_pending_out_bytes``);
- compiled-program scratch is claimed per jitted executable under
  ``("scratch", (name, shape-key))`` from the XLA compile-time memory
  analysis.

Claims are pure bookkeeping — the ledger never allocates.  What makes it
trustworthy is that every claim mirrors a *tracked* allocation (the
tpulab.memory / tpulab.tpu.allocators framework or a tenant's own
byte-accurate gauge), so :meth:`DeviceHBMLedger.verify` can cross-check
the ledger against the live gauges at any time; the hbm tests enforce
the invariant after every arbiter operation.

The key is ``(tenant, tag)`` rather than a flat name on purpose: the 2D
mesh work (ROADMAP item 3) makes HBM a per-axis quantity, and a keyed
ledger extends to ``(tenant, tag, axis)`` claims without a refactor.
"""

from __future__ import annotations

import threading
from typing import Dict, Hashable, List, Tuple

__all__ = ["DeviceHBMLedger"]


class DeviceHBMLedger:
    """Byte-accurate ``(tenant, tag) -> bytes`` device-memory ledger.

    ``capacity_bytes`` is the device budget the arbiter trades within
    (weights + KV pages + compiled scratch).  The ledger itself never
    refuses a claim — enforcement (pressure, denial) is the
    :class:`~tpulab.hbm.arbiter.HBMArbiter`'s job — but headroom can go
    negative and :meth:`headroom_bytes` reports it honestly.

    Thread-safe; every mutation notifies waiters (the arbiter blocks on
    :meth:`wait_for_change` while write-behind reclaims land).
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            raise ValueError("capacity_bytes must be > 0")
        self.capacity_bytes = int(capacity_bytes)
        self._claims: Dict[Tuple[str, Hashable], int] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    # -- mutations -----------------------------------------------------------
    def claim(self, tenant: str, tag: Hashable, nbytes: int) -> None:
        """Record ``nbytes`` held by ``(tenant, tag)``.  Claiming an
        existing key is an error — use :meth:`resize` (a silent
        double-claim is exactly the accounting bug this ledger exists to
        make impossible)."""
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("claim bytes must be >= 0")
        key = (tenant, tag)
        with self._cv:
            if key in self._claims:
                raise ValueError(f"claim {key!r} already recorded "
                                 f"({self._claims[key]} bytes)")
            self._claims[key] = nbytes
            self._cv.notify_all()

    def release(self, tenant: str, tag: Hashable) -> int:
        """Drop a claim; returns the bytes it held (0 for unknown keys —
        release is idempotent so degraded paths can always call it)."""
        with self._cv:
            n = self._claims.pop((tenant, tag), 0)
            if n:
                self._cv.notify_all()
            return n

    def resize(self, tenant: str, tag: Hashable, nbytes: int) -> None:
        """Re-record a claim at its tenant's current tracked size (elastic
        pool grow/shrink).  Unknown keys are created — resize is the
        idempotent upsert the byte-gauge mirrors use."""
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("claim bytes must be >= 0")
        with self._cv:
            if nbytes == 0:
                self._claims.pop((tenant, tag), None)
            else:
                self._claims[(tenant, tag)] = nbytes
            self._cv.notify_all()

    # -- views ---------------------------------------------------------------
    @property
    def total_claimed(self) -> int:
        with self._lock:
            return sum(self._claims.values())

    @property
    def headroom_bytes(self) -> int:
        """``capacity - total claimed``; may be negative (over-committed
        discovery, e.g. scratch measured after the fact) — consumers clamp
        where a negative figure has no meaning."""
        with self._lock:
            return self.capacity_bytes - sum(self._claims.values())

    def tenant_bytes(self, tenant: str) -> int:
        with self._lock:
            return sum(n for (t, _), n in self._claims.items()
                       if t == tenant)

    def tenant_claims(self, tenant: str) -> int:
        """Number of live claims a tenant holds."""
        with self._lock:
            return sum(1 for (t, _) in self._claims if t == tenant)

    def claims(self) -> List[Tuple[str, Hashable, int]]:
        """Snapshot of every live claim (tenant, tag, bytes)."""
        with self._lock:
            return [(t, tag, n) for (t, tag), n in self._claims.items()]

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted({t for (t, _) in self._claims})

    # -- the invariant -------------------------------------------------------
    def verify(self, gauges: Dict[str, int]) -> Dict[str, Tuple[int, int]]:
        """Cross-check per-tenant claimed bytes against live tracked
        gauges (``{tenant: gauge_bytes}``).  Returns the mismatches as
        ``{tenant: (claimed, gauge)}`` — empty means the ledger agrees
        byte-for-byte with every gauge handed in.  The hbm tests call
        this after EVERY arbiter op; it is also the contract the Status
        RPC's ``free_hbm_bytes`` rests on."""
        out: Dict[str, Tuple[int, int]] = {}
        for tenant, gauge in gauges.items():
            claimed = self.tenant_bytes(tenant)
            if claimed != int(gauge):
                out[tenant] = (claimed, int(gauge))
        return out

    # -- waiting -------------------------------------------------------------
    def wait_for_change(self, timeout: float) -> None:
        """Block until any claim changes (write-behind landings release
        claims from transfer-collector threads) or ``timeout`` elapses."""
        with self._cv:
            self._cv.wait(timeout=timeout)
