"""Compiled-program scratch measurement (the third HBM tenant).

XLA executables reserve temporary device buffers (attention score
blocks, reduce scratch, donation shuffles) that neither the KV pool nor
the weight multiplexer ever sees — on a tight device the "free" headroom
admission believed in was partly these invisible temps.  This module
makes them a first-class ledger tenant: :class:`MeasuredJit` wraps a
``jax.jit`` callable and, once per distinct argument-shape signature,
lowers + compiles the program and records its compile-time
``temp_size_in_bytes`` with the arbiter under
``("scratch", (name, shape-key))``.

Cost model: measuring pays one extra lower+compile per (jit, signature)
— it is only armed when an :class:`~tpulab.hbm.HBMArbiter` with
``measure_scratch=True`` is attached to the engine; unarbitrated
engines get the plain ``jax.jit`` callable and pay nothing.  Any gap in
the introspection API (backends without ``memory_analysis``) degrades
to recording a zero-byte claim: the jit is still visible in the ledger
inventory, its size just unknown — never a serving failure.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Tuple

log = logging.getLogger("tpulab.hbm")

__all__ = ["MeasuredJit", "scratch_bytes_of", "shape_key"]


def shape_key(args: Tuple[Any, ...]) -> Tuple:
    """Hashable signature of a jit call: per-leaf (shape, dtype) for
    arrays, the value itself for static-ish leaves (None, ints) — the
    same distinctions jax.jit specializes on for these call sites."""
    import jax
    out = []
    for leaf in jax.tree_util.tree_leaves(
            args, is_leaf=lambda x: x is None):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            out.append(repr(leaf))
    return tuple(out)


def scratch_bytes_of(compiled) -> int:
    """Temp (scratch) HBM of one compiled XLA executable, from the
    compile-time memory analysis; 0 when the backend cannot say."""
    try:
        ma = compiled.memory_analysis()
        return int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    except Exception:  # pragma: no cover - backend-dependent API
        return 0


class MeasuredJit:
    """A ``jax.jit`` callable that records its compiled scratch with an
    :class:`~tpulab.hbm.HBMArbiter` once per argument-shape signature.

    The measurement path (``jitted.lower(*args).compile()``) runs BEFORE
    the first real call for that signature, so donated buffers are still
    live when the avals are read; the recorded claim is
    ``("scratch", (name, signature))`` sized at the executable's
    ``temp_size_in_bytes``.  The call itself always goes through the
    plain jitted callable — measuring can never change execution."""

    __slots__ = ("_jitted", "_arbiter", "_name", "_seen")

    def __init__(self, jitted, arbiter, name: str):
        self._jitted = jitted
        self._arbiter = arbiter
        self._name = name
        self._seen: Dict[Tuple, bool] = {}

    def __call__(self, *args):
        key = None
        try:
            key = shape_key(args)
        except Exception:  # pragma: no cover - exotic leaves: skip measure
            pass
        if key is not None and key not in self._seen:
            self._seen[key] = True
            nbytes = 0
            try:
                nbytes = scratch_bytes_of(
                    self._jitted.lower(*args).compile())
            except Exception as e:  # noqa: BLE001 - degrade to 0 bytes
                log.debug("scratch measure failed for %s: %r",
                          self._name, e)
            self._arbiter.record_scratch((self._name, key), nbytes)
        return self._jitted(*args)

    # pass-throughs some callers poke at (parity with jax.jit objects)
    def lower(self, *args, **kw):  # pragma: no cover - convenience
        return self._jitted.lower(*args, **kw)
