"""Unified device-HBM economy (docs/PERFORMANCE.md "HBM economy").

trtlab's foundation is ONE allocator/descriptor/arena framework that every
higher layer rents from (PAPER.md layer map §0); tpulab reproduced that
for host memory, but device HBM grew into three fiefdoms — the
:class:`~tpulab.engine.paged.PagedKVPool` pre-carves pages, the
:class:`~tpulab.modelstore.WeightMultiplexer` budgets weights *next to*
(not with) KV accounting, and compiled-program scratch was invisible to
both.  This package is the missing common ground:

- :class:`DeviceHBMLedger` — a byte-accurate device-memory ledger.
  Every claim is keyed by ``(tenant, tag)`` (the 2D-mesh work will make
  the key per-axis without another refactor) and mirrors a real tracked
  allocation, so the ledger can be *verified* against the device
  allocator gauges at any time.
- :class:`HBMArbiter` — the pressure protocol between tenants.  A hot
  model needing residency can force cold KV pages to demote to the host
  tier (the KV tier's swap-out path), a KV burst can evict a cold
  unleased model (the weight multiplexer's swap-out path), and the
  admission frontend consults ONE honest headroom number instead of two
  optimistic per-tenant estimates.
"""

from tpulab.hbm.arbiter import (KV_TENANT, SCRATCH_TENANT,  # noqa: F401
                                WEIGHTS_TENANT, HBMArbiter,
                                benchmark_hbm_arbiter)
from tpulab.hbm.ledger import DeviceHBMLedger  # noqa: F401
from tpulab.hbm.scratch import MeasuredJit, scratch_bytes_of  # noqa: F401

__all__ = ["DeviceHBMLedger", "HBMArbiter", "MeasuredJit",
           "scratch_bytes_of", "benchmark_hbm_arbiter",
           "KV_TENANT", "WEIGHTS_TENANT", "SCRATCH_TENANT"]
