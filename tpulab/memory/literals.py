"""Byte-size literals and parsing (reference literals.h, core utils.h:43)."""

from __future__ import annotations

import re

KiB = 1024
MiB = 1024 * KiB
GiB = 1024 * MiB

_UNITS = {
    "b": 1,
    "kb": 1000, "kib": KiB,
    "mb": 1000 ** 2, "mib": MiB,
    "gb": 1000 ** 3, "gib": GiB,
    "tb": 1000 ** 4, "tib": GiB * 1024,
}

_SIZE_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([a-zA-Z]*)\s*$")


def string_to_bytes(s: str | int) -> int:
    """Parse '10MiB'-style size strings (reference core utils.cc StringToBytes).

    Accepts bare integers, decimal values, and b/kb/kib/mb/mib/gb/gib/tb/tib
    suffixes (case-insensitive).
    """
    if isinstance(s, int):
        return s
    m = _SIZE_RE.match(s)
    if not m:
        raise ValueError(f"cannot parse byte size: {s!r}")
    value, unit = m.groups()
    unit = unit.lower() or "b"
    if unit not in _UNITS:
        raise ValueError(f"unknown byte-size unit {unit!r} in {s!r}")
    return int(float(value) * _UNITS[unit])


def bytes_to_string(n: int) -> str:
    """Human-readable byte size (reference core utils.cc BytesToString)."""
    x = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(x) < 1024.0 or unit == "TiB":
            if unit == "B":
                return f"{int(x)} B"
            return f"{x:.2f} {unit}"
        x /= 1024.0
    raise AssertionError


def align_up(value: int, alignment: int) -> int:
    """Round up to an alignment boundary (reference align.h)."""
    if alignment <= 0 or (alignment & (alignment - 1)):
        raise ValueError(f"alignment must be a positive power of two: {alignment}")
    return (value + alignment - 1) & ~(alignment - 1)


def align_down(value: int, alignment: int) -> int:
    if alignment <= 0 or (alignment & (alignment - 1)):
        raise ValueError(f"alignment must be a positive power of two: {alignment}")
    return value & ~(alignment - 1)


def is_aligned(value: int, alignment: int) -> bool:
    return (value % alignment) == 0


def ilog2(n: int) -> int:
    """Integer log2 (reference utils.h ilog2)."""
    if n <= 0:
        raise ValueError("ilog2 requires a positive value")
    return n.bit_length() - 1
