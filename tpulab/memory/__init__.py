"""tpulab.memory — the allocator framework.

A device-typed, descriptor-based memory framework with the capability set of
``trtlab/memory`` (reference include/trtlab/memory/*.h, ~12.6k LoC C++):

- compile-time-style memory *kinds* carrying a DLPack device type and alignment
  policy (reference memory_type.h:93-129)
- move-only owning ``Descriptor`` handles that release to their allocator on
  destruction (reference descriptor.h:40-99)
- a type-erased ``IAllocator`` interface + ``make_allocator`` facade adding
  thread safety and tracking (reference allocator.h:41-290)
- raw allocators (mmap/malloc, aligned, transparent huge pages), composable
  block allocators, caching block arenas, a block manager for address->block
  lookup (reference block_allocators.h, block_arena.h, block_manager.h)
- a fixed-node ``MemoryPool`` free-list (reference memory_pool.h:37-295)
- the serving-critical ``TransactionalAllocator`` — rotating ref-counted bump
  stacks for per-request tensor scratch (reference transactional_allocator.h:155-367)
- a best-fit ``BFitAllocator`` for long-lived variable-size allocations such as
  weights (reference bfit_allocator.h:20-123)
- allocation trackers and leak-checking/raii wrappers
  (reference trackers.h, tracking.h, raii_allocator.h)

The framework is device-agnostic over ``MemoryType``: the TPU build adds
``TpuMemory`` (HBM via JAX/PjRt buffers) and ``HostPinnedMemory`` (staging) in
:mod:`tpulab.tpu` without changing any allocator logic — exactly how the
reference layers ``trtlab/cuda`` memory types onto ``trtlab/memory``.

A C++17 implementation of the hot allocators (arena, transactional, pool) lives
in ``cpp/`` and is used transparently when built (see tpulab.memory.native).
"""

from tpulab.memory.literals import KiB, MiB, GiB, bytes_to_string, string_to_bytes
from tpulab.memory.memory_type import (
    MemoryType,
    HostMemory,
    AnyMemory,
    DLDeviceType,
    is_memory_type,
    is_host_accessible,
)
from tpulab.memory.descriptor import Descriptor, IAllocator
from tpulab.memory.raw_allocators import (
    MallocAllocator,
    AlignedAllocator,
    HugePageAllocator,
)
from tpulab.memory.block import (
    MemoryBlock,
    SingleBlockAllocator,
    FixedSizeBlockAllocator,
    GrowingBlockAllocator,
    CountLimitedBlockAllocator,
    SizeLimitedBlockAllocator,
    is_block_allocator,
)
from tpulab.memory.arena import BlockArena, BlockStack, BlockManager
from tpulab.memory.allocator import make_allocator, AllocatorImpl
from tpulab.memory.memory_pool import MemoryPool
from tpulab.memory.transactional import TransactionalAllocator, make_transactional_allocator
from tpulab.memory.bfit import BFitAllocator
from tpulab.memory.trackers import SizeTracker, TrackedBlockAllocator
from tpulab.memory.raii import RaiiAllocator
from tpulab.memory.debugging import (
    set_leak_handler,
    get_leak_handler,
    OutOfMemory,
    BadAllocationSize,
    LeakError,
)

__all__ = [
    "KiB", "MiB", "GiB", "bytes_to_string", "string_to_bytes",
    "MemoryType", "HostMemory", "AnyMemory", "DLDeviceType",
    "is_memory_type", "is_host_accessible",
    "Descriptor", "IAllocator",
    "MallocAllocator", "AlignedAllocator", "HugePageAllocator",
    "MemoryBlock", "SingleBlockAllocator", "FixedSizeBlockAllocator",
    "GrowingBlockAllocator", "CountLimitedBlockAllocator",
    "SizeLimitedBlockAllocator", "is_block_allocator",
    "BlockArena", "BlockStack", "BlockManager",
    "make_allocator", "AllocatorImpl",
    "MemoryPool",
    "TransactionalAllocator", "make_transactional_allocator",
    "BFitAllocator",
    "SizeTracker", "TrackedBlockAllocator",
    "RaiiAllocator",
    "set_leak_handler", "get_leak_handler",
    "OutOfMemory", "BadAllocationSize", "LeakError",
]
