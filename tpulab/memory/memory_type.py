"""Memory *kinds*: device-typed tags carried by every allocator and descriptor.

Mirrors the reference's compile-time memory type system
(reference trtlab/memory/include/trtlab/memory/memory_type.h:93-129 and
detail:40-87): each memory kind declares its DLPack device type, minimum
allocation alignment, and access alignment.  Allocators are parameterized by a
memory type; descriptors report theirs; copies dispatch on (src kind, dst kind).

TPU additions (the analog of trtlab/cuda/include/.../device_memory.h:36-84) live
in :mod:`tpulab.tpu.memory_types`: ``TpuMemory`` (device HBM via a JAX/PjRt
buffer) and ``HostPinnedMemory`` (page-aligned staging memory for fast
host->HBM transfer).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum


class DLDeviceType(IntEnum):
    """DLPack device types (subset) + a private TPU code.

    DLPack has no official TPU device code; we use an ext-dev code the way
    other out-of-tree backends do.  kDLCPU/kDLCUDAHost values follow dlpack.h.
    """

    kDLCPU = 1
    kDLCUDA = 2
    kDLCUDAHost = 3
    kDLExtDev = 12
    kDLTPU = 99  # private: JAX/PjRt-managed HBM


@dataclass(frozen=True)
class MemoryType:
    """A memory kind: name + DLPack device type + alignment policy.

    ``min_allocation_alignment`` is the alignment every allocation of this kind
    is rounded up to; ``access_alignment`` is the guaranteed pointer alignment
    (reference memory_type.h: host_memory 8B; cuda device_memory 256B/64B).
    """

    name: str
    device_type: DLDeviceType
    min_allocation_alignment: int = 8
    access_alignment: int = 8
    host_accessible: bool = True

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"MemoryType({self.name})"


#: Plain host memory — 8-byte aligned, kDLCPU (reference memory_type.h:93-129).
HostMemory = MemoryType("host", DLDeviceType.kDLCPU, 8, 8, True)

#: Wildcard used by type-erased interfaces (reference any_memory).
AnyMemory = MemoryType("any", DLDeviceType.kDLCPU, 1, 1, True)


def is_memory_type(obj: object) -> bool:
    """Reference ``is_memory_type`` trait."""
    return isinstance(obj, MemoryType)


def is_host_accessible(mt: MemoryType) -> bool:
    """Can the host build a memoryview over this kind of memory?"""
    return mt.host_accessible
