"""Raw host allocators (reference malloc_allocator.h:39,
posix_aligned_allocator.h:13-19, huge_page_allocator.h:9-10).

Raw allocators are the leaves of the composition chain: they produce real
memory.  In this build host memory comes from ``mmap`` (page-aligned, so any
alignment <= 4096 is free) with an over-allocate-and-offset path for larger
alignments.  ``HugePageAllocator`` requests transparent huge pages via
``madvise(MADV_HUGEPAGE)`` — the honest Linux equivalent of the reference's
2MiB THP allocator.

A raw allocator is *stateful* (it owns its mappings) but cheap; the
``make_allocator`` facade adds thread-safety and ``IAllocator`` erasure
(reference allocator.h / allocator_traits.h RawAllocator concept:
allocate_node/deallocate_node, memory_type, is_stateful).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import mmap
from typing import Dict, Tuple

from tpulab.memory.debugging import InvalidPointer, OutOfMemory
from tpulab.memory.literals import align_up
from tpulab.memory.memory_type import HostMemory, MemoryType

_libc = ctypes.CDLL(None, use_errno=True)

MADV_HUGEPAGE = 14


def _addr_of(buf: mmap.mmap) -> int:
    return ctypes.addressof(ctypes.c_char.from_buffer(buf))


class MallocAllocator:
    """General-purpose host allocator over mmap (reference malloc_allocator.h:39).

    RawAllocator concept: allocate_node/deallocate_node; stateful (owns maps).
    """

    memory_type: MemoryType = HostMemory
    is_stateful = True

    def __init__(self):
        # addr -> (mmap object, base address)
        self._maps: Dict[int, Tuple[mmap.mmap, int]] = {}

    # RawAllocator concept --------------------------------------------------
    def allocate_node(self, size: int, alignment: int = 8) -> int:
        if size <= 0:
            raise OutOfMemory(type(self).__name__, size, "(non-positive size)")
        alignment = max(alignment, self.memory_type.min_allocation_alignment)
        span = size if alignment <= mmap.PAGESIZE else size + alignment
        try:
            m = mmap.mmap(-1, span)
        except OSError as e:
            raise OutOfMemory(type(self).__name__, span, str(e)) from e
        base = _addr_of(m)
        addr = align_up(base, alignment)
        self._post_map(addr, span - (addr - base))
        self._maps[addr] = (m, base)
        return addr

    def deallocate_node(self, addr: int, size: int, alignment: int = 8) -> None:
        try:
            m, _base = self._maps.pop(addr)
        except KeyError:
            raise InvalidPointer(f"0x{addr:x} not allocated by {type(self).__name__}")
        m.close()

    def _post_map(self, addr: int, span: int) -> None:
        """Hook for subclasses (huge pages, first-touch)."""

    def view(self, addr: int, size: int) -> memoryview:
        from tpulab.memory.descriptor import host_view
        return host_view(addr, size)

    def owns(self, addr: int) -> bool:
        return addr in self._maps

    @property
    def live_allocations(self) -> int:
        return len(self._maps)

    def max_node_size(self) -> int:
        return 1 << 48


class AlignedAllocator(MallocAllocator):
    """Fixed-alignment host allocator (reference posix_aligned_allocator<Align>)."""

    def __init__(self, alignment: int = 64):
        super().__init__()
        if alignment & (alignment - 1):
            raise ValueError("alignment must be a power of two")
        self.alignment = alignment

    def allocate_node(self, size: int, alignment: int = 0) -> int:
        return super().allocate_node(size, max(alignment, self.alignment))


class HugePageAllocator(MallocAllocator):
    """Transparent-huge-page host allocator (reference huge_page_allocator<2MiB>).

    Aligns every mapping to 2 MiB and advises the kernel to back it with THP.
    Falls back silently to normal pages where THP is unavailable.
    """

    HUGE_PAGE_SIZE = 2 * 1024 * 1024

    def allocate_node(self, size: int, alignment: int = 0) -> int:
        size = align_up(size, self.HUGE_PAGE_SIZE)
        return super().allocate_node(size, max(alignment, self.HUGE_PAGE_SIZE))

    def _post_map(self, addr: int, span: int) -> None:
        try:
            _libc.madvise(ctypes.c_void_p(addr), ctypes.c_size_t(span), MADV_HUGEPAGE)
        except Exception:  # pragma: no cover - advisory only
            pass


class FirstTouchAllocator(MallocAllocator):
    """NUMA first-touch adaptor (reference core first_touch_allocator.h:34-60).

    Touches (zero-fills) every page at allocation time from the calling thread
    so pages land on that thread's NUMA node.  Combine with
    :mod:`tpulab.core.affinity` to bind the touching thread to the TPU host's
    local node before allocating staging buffers.
    """

    def __init__(self, fill: int = 0):
        super().__init__()
        self._fill = fill

    def _post_map(self, addr: int, span: int) -> None:
        ctypes.memset(ctypes.c_void_p(addr), self._fill, span)
