"""Shared-memory allocator: cross-process zero-copy ingress.

The reference's SysV shared-memory path (core sysv_allocator.cc:46-70
shmget/shmat; examples/02 server.cc:110-137 uses it so clients hand the
server tensor data without a socket copy).  The modern Linux equivalent used
here is POSIX shm via ``multiprocessing.shared_memory`` — same capability:
a producer process fills a named segment; the serving process maps it and
binds tensors over it zero-copy.

``SharedMemoryAllocator`` satisfies the RawAllocator concept (composes with
descriptors/arenas); ``attach()`` maps an existing segment by name.
"""

from __future__ import annotations

import ctypes
from multiprocessing import shared_memory
from typing import Dict, Optional, Tuple

import numpy as np

from tpulab.memory.debugging import InvalidPointer, OutOfMemory
from tpulab.memory.descriptor import Descriptor, host_view
from tpulab.memory.memory_type import DLDeviceType, MemoryType

SharedHostMemory = MemoryType(
    name="host_shared",
    device_type=DLDeviceType.kDLCPU,
    min_allocation_alignment=4096,
    access_alignment=64,
    host_accessible=True,
)


def _export(shm: shared_memory.SharedMemory) -> Tuple[int, object]:
    """(address, holder) — the holder keeps the buffer export alive and must
    be dropped before the segment can close."""
    holder = ctypes.c_char.from_buffer(shm.buf)
    return ctypes.addressof(holder), holder


class SharedMemoryAllocator:
    """RawAllocator over named POSIX shm segments (reference sysv_allocator)."""

    is_stateful = True
    memory_type = SharedHostMemory

    def __init__(self, prefix: str = "tpulab"):
        self._prefix = prefix
        self._segments: Dict[int, Tuple[shared_memory.SharedMemory, object]] = {}
        self._count = 0

    # -- RawAllocator concept ----------------------------------------------
    def allocate_node(self, size: int, alignment: int = 0) -> int:
        if size <= 0:
            raise OutOfMemory("SharedMemoryAllocator", size)
        import os
        import uuid
        # pid+uuid: unique across forked children (id(self) is inherited)
        name = f"{self._prefix}_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self._count += 1
        try:
            seg = shared_memory.SharedMemory(name=name, create=True, size=size)
        except OSError as e:
            raise OutOfMemory("SharedMemoryAllocator", size, str(e)) from e
        addr, holder = _export(seg)
        self._segments[addr] = (seg, holder)
        return addr

    def deallocate_node(self, addr: int, size: int = 0,
                        alignment: int = 0) -> None:
        entry = self._segments.pop(addr, None)
        if entry is None:
            raise InvalidPointer(f"0x{addr:x} is not a shm segment here")
        seg = entry[0]
        del entry  # drop the tuple -> the export holder frees -> unmap works
        import gc
        gc.collect()  # the ctypes<->memoryview holder pair is a cycle
        seg.close()
        try:
            seg.unlink()
        except FileNotFoundError:  # a peer already unlinked it
            pass

    def view(self, addr: int, size: int):
        return host_view(addr, size)

    def segment_name(self, addr: int) -> str:
        """The name a peer process attaches with."""
        return self._segments[addr][0].name

    # -- cross-process attach ----------------------------------------------
    @staticmethod
    def attach(name: str) -> "AttachedSegment":
        return AttachedSegment(name)

    def close(self) -> None:
        for addr in list(self._segments):
            try:
                self.deallocate_node(addr)
            except Exception:  # pragma: no cover
                pass


class AttachedSegment:
    """A peer-process mapping of a named segment (reference shmat side)."""

    def __init__(self, name: str):
        import gc
        self._shm = shared_memory.SharedMemory(name=name)
        self.name = name
        # peers must NOT unlink on exit — the owner does (py3.12 has no
        # track=False; unregister from the resource tracker instead)
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(self._shm._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker internals
            pass
        # capture the base address once, then release the export so views
        # built via from_address never block close() (raw-pointer contract,
        # same as everywhere else in the framework)
        addr, holder = _export(self._shm)
        self._addr = addr
        del holder
        gc.collect()

    @property
    def size(self) -> int:
        return self._shm.size

    def numpy(self, dtype=np.uint8, shape=None) -> np.ndarray:
        arr = np.frombuffer(host_view(self._addr, self._shm.size), dtype=dtype)
        return arr.reshape(shape) if shape is not None else arr

    def close(self) -> None:
        self._shm.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
