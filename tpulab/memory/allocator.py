"""The allocator facade: ``make_allocator`` (reference allocator.h:41-290).

Wraps any RawAllocator (allocate_node/deallocate_node + memory_type) in a
thread-safe shared object implementing the type-erased :class:`IAllocator`
interface, so descriptors can hold it and release from any thread.  Mirrors
``allocator_detail::smart_storage`` + ``allocator_impl`` + ``make_allocator``.

Threading policy (reference threading.h:27-112): stateless raw allocators get
the ``no_mutex`` policy; stateful ones are serialized with a real lock.  Pass
``thread_safe=False`` to force the no-mutex policy when the caller provides
external synchronization.
"""

from __future__ import annotations

import threading
from contextlib import nullcontext
from typing import Optional

from tpulab.memory.descriptor import Descriptor, IAllocator
from tpulab.memory.memory_type import MemoryType


def _is_stateful(raw) -> bool:
    return bool(getattr(raw, "is_stateful", True))


class AllocatorImpl(IAllocator):
    """IAllocator over a RawAllocator with a threading policy
    (reference allocator_impl / smart_storage)."""

    def __init__(self, raw, thread_safe: Optional[bool] = None):
        if not callable(getattr(raw, "allocate_node", None)):
            raise TypeError(f"{raw!r} does not satisfy the RawAllocator concept")
        self._raw = raw
        self.memory_type: MemoryType = raw.memory_type
        if thread_safe is None:
            thread_safe = _is_stateful(raw)
        self._lock = threading.Lock() if thread_safe else nullcontext()

    @property
    def raw(self):
        return self._raw

    def allocate(self, size: int, alignment: int = 0) -> int:
        alignment = alignment or self.memory_type.min_allocation_alignment
        with self._lock:
            return self._raw.allocate_node(size, alignment)

    def deallocate(self, addr: int, size: int, alignment: int = 0) -> None:
        alignment = alignment or self.memory_type.min_allocation_alignment
        with self._lock:
            self._raw.deallocate_node(addr, size, alignment)

    def max_alignment(self) -> int:
        fn = getattr(self._raw, "max_alignment", None)
        return fn() if callable(fn) else self.memory_type.access_alignment

    def view(self, addr: int, size: int):
        fn = getattr(self._raw, "view", None)
        if callable(fn):
            return fn(addr, size)
        return super().view(addr, size)


def make_allocator(raw, thread_safe: Optional[bool] = None) -> AllocatorImpl:
    """The universal entry point (reference make_allocator, allocator.h:138+)."""
    if isinstance(raw, AllocatorImpl):
        return raw
    return AllocatorImpl(raw, thread_safe=thread_safe)
