"""RAII allocator: records live descriptors, frees leftovers on close
(reference raii_allocator.h:41-155)."""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from tpulab.memory.debugging import report_leak
from tpulab.memory.descriptor import Descriptor, IAllocator
from tpulab.memory.memory_type import MemoryType


class RaiiAllocator(IAllocator):
    """Tracks outstanding allocations and reclaims them on close()."""

    def __init__(self, inner: IAllocator, name: str = "raii"):
        self._inner = inner
        self.name = name
        self.memory_type: MemoryType = inner.memory_type
        self._lock = threading.Lock()
        self._live: Dict[int, Tuple[int, int]] = {}  # addr -> (size, alignment)
        self._closed = False

    def allocate(self, size: int, alignment: int = 0) -> int:
        addr = self._inner.allocate(size, alignment)
        with self._lock:
            self._live[addr] = (size, alignment)
        return addr

    def deallocate(self, addr: int, size: int, alignment: int = 0) -> None:
        with self._lock:
            self._live.pop(addr, None)
        self._inner.deallocate(addr, size, alignment)

    def view(self, addr: int, size: int):
        return self._inner.view(addr, size)

    @property
    def live_allocations(self) -> int:
        with self._lock:
            return len(self._live)

    def close(self) -> None:
        """Free anything still outstanding (reference raii_storage dtor)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            leftovers = list(self._live.items())
            self._live.clear()
        if leftovers:
            report_leak(self.name, sum(s for _, (s, _a) in leftovers))
            for addr, (size, alignment) in leftovers:
                self._inner.deallocate(addr, size, alignment)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
