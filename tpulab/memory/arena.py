"""Block arena / stack / manager (reference block_arena.h:47-170,
block_stack.h:25-146, block_manager.h:41-47).

- ``BlockArena``: sits on a block allocator and *caches* freed blocks (cached
  policy) or passes frees straight through (uncached), so hot paths recycle
  device/host blocks without touching the raw allocator.
- ``BlockStack``: LIFO of live blocks with a bump cursor in the top block —
  the building element of per-request buffer stacks.
- ``BlockManager``: address -> block lookup over all registered blocks, used by
  allocators that must answer "which block owns this pointer".
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional

from tpulab.memory.block import MemoryBlock, is_block_allocator
from tpulab.memory.debugging import InvalidPointer, OutOfMemory
from tpulab.memory.literals import align_up
from tpulab.memory.memory_type import MemoryType


class BlockArena:
    """Caching arena over a block allocator (reference block_arena / block_cache).

    ``cached=True`` keeps deallocated blocks on a free list and serves future
    ``allocate_block`` calls from it (reference cached_arena);
    ``cached=False`` is the pass-through policy (uncached_arena).
    """

    def __init__(self, block_allocator, cached: bool = True):
        if not is_block_allocator(block_allocator):
            raise TypeError(f"{block_allocator!r} is not a block allocator")
        self._inner = block_allocator
        self._cached = cached
        self._cache: List[MemoryBlock] = []
        self._live = 0

    @property
    def memory_type(self) -> MemoryType:
        return self._inner.memory_type

    @property
    def next_block_size(self) -> int:
        return self._inner.next_block_size

    @property
    def cached_blocks(self) -> int:
        return len(self._cache)

    @property
    def live_blocks(self) -> int:
        return self._live

    def allocate_block(self) -> MemoryBlock:
        """Serve from cache if a cached block is big enough for the inner
        allocator's current block size (matters under growing allocators)."""
        self._live += 1
        want = self._inner.next_block_size
        for i in range(len(self._cache) - 1, -1, -1):
            if self._cache[i].size >= want:
                return self._cache.pop(i)
        try:
            return self._inner.allocate_block()
        except Exception:
            self._live -= 1
            raise

    def deallocate_block(self, block: MemoryBlock) -> None:
        self._live -= 1
        if self._cached:
            self._cache.append(block)
        else:
            self._inner.deallocate_block(block)

    def shrink_to_fit(self) -> int:
        """Drop the cache back to the underlying allocator; returns bytes freed."""
        freed = 0
        while self._cache:
            block = self._cache.pop()
            freed += block.size
            self._inner.deallocate_block(block)
        return freed


class BlockStack:
    """LIFO stack of blocks with bump allocation in the top block
    (reference memory_block_stack:25-146).

    ``allocate(size, alignment)`` bumps within the top block, pushing a new
    block from the arena when the top is exhausted.  ``pop()`` releases the top
    block; ``reset()`` releases everything.  This is the carving mechanism for
    per-request binding stacks (reference v1 FixedBuffers).
    """

    def __init__(self, arena):
        self._arena = arena
        self._blocks: List[MemoryBlock] = []
        self._cursors: List[int] = []  # bump offset per block, parallel to _blocks

    @property
    def depth(self) -> int:
        return len(self._blocks)

    @property
    def top(self) -> Optional[MemoryBlock]:
        return self._blocks[-1] if self._blocks else None

    def push(self) -> MemoryBlock:
        block = self._arena.allocate_block()
        self._blocks.append(block)
        self._cursors.append(0)
        return block

    def pop(self) -> None:
        if not self._blocks:
            raise InvalidPointer("pop from empty block stack")
        self._arena.deallocate_block(self._blocks.pop())
        self._cursors.pop()

    def allocate(self, size: int, alignment: int = 8) -> int:
        if size <= 0:
            raise OutOfMemory("BlockStack", size, "(non-positive size)")
        if not self._blocks:
            self.push()
        top = self._blocks[-1]
        start = align_up(top.addr + self._cursors[-1], alignment) - top.addr
        if start + size > top.size:
            if size > self._arena.next_block_size:
                raise OutOfMemory("BlockStack", size,
                                  f"(exceeds block size {self._arena.next_block_size})")
            self.push()
            top = self._blocks[-1]
            start = align_up(top.addr, alignment) - top.addr
            if start + size > top.size:
                raise OutOfMemory("BlockStack", size, "(alignment overflow)")
        self._cursors[-1] = start + size
        return top.addr + start

    def reset(self) -> None:
        while self._blocks:
            self.pop()

    @property
    def available_in_top(self) -> int:
        if not self._blocks:
            return 0
        return self._blocks[-1].size - self._cursors[-1]


class BlockManager:
    """Address -> owning block lookup (reference block_manager.h:41-47)."""

    def __init__(self):
        self._starts: List[int] = []          # sorted block start addrs
        self._blocks: Dict[int, MemoryBlock] = {}

    def add_block(self, block: MemoryBlock) -> None:
        if block.addr in self._blocks:
            raise InvalidPointer(f"block at 0x{block.addr:x} already registered")
        bisect.insort(self._starts, block.addr)
        self._blocks[block.addr] = block

    def drop_block(self, addr: int) -> MemoryBlock:
        block = self._blocks.pop(addr, None)
        if block is None:
            raise InvalidPointer(f"no block registered at 0x{addr:x}")
        self._starts.remove(addr)
        return block

    def find_block(self, addr: int) -> Optional[MemoryBlock]:
        """The block containing ``addr``, if any."""
        i = bisect.bisect_right(self._starts, addr) - 1
        if i < 0:
            return None
        block = self._blocks[self._starts[i]]
        return block if block.contains(addr) else None

    def owns(self, addr: int) -> bool:
        return self.find_block(addr) is not None

    @property
    def size(self) -> int:
        return len(self._blocks)

    def blocks(self) -> List[MemoryBlock]:
        return [self._blocks[a] for a in self._starts]
