"""Memory blocks + composable block allocators.

Mirrors reference block_allocators.h:25-120+ and memory_block.h:19-104: a
*block allocator* produces fixed or growing ``MemoryBlock``s from a raw
allocator, and compositions bound the count or total size.  Block allocators
feed arenas (:mod:`tpulab.memory.arena`), pools, and the transactional
allocator.

The block layer is fully device-agnostic: a block allocator over the TPU raw
allocator (tpulab.tpu.allocators) yields HBM blocks the same way a malloc-based
one yields host blocks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from tpulab.memory.debugging import OutOfMemory
from tpulab.memory.memory_type import MemoryType


@dataclass
class MemoryBlock:
    """{addr, size} span produced by a block allocator (reference memory_block.h)."""

    addr: int
    size: int
    #: opaque backing object for device blocks (e.g. a JAX array)
    handle: Any = None

    def contains(self, addr: int) -> bool:
        return self.addr <= addr < self.addr + self.size

    @property
    def end(self) -> int:
        return self.addr + self.size


def is_block_allocator(obj: object) -> bool:
    """Reference ``is_block_allocator`` trait: allocate_block/deallocate_block."""
    return callable(getattr(obj, "allocate_block", None)) and callable(
        getattr(obj, "deallocate_block", None))


class _BlockAllocatorBase:
    def __init__(self, raw_allocator, block_size: int, alignment: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        self._raw = raw_allocator
        self._block_size = block_size
        self._alignment = alignment or raw_allocator.memory_type.access_alignment

    @property
    def memory_type(self) -> MemoryType:
        return self._raw.memory_type

    @property
    def next_block_size(self) -> int:
        return self._block_size

    @property
    def raw_allocator(self):
        return self._raw

    def _make_block(self, size: int) -> MemoryBlock:
        addr = self._raw.allocate_node(size, self._alignment)
        return MemoryBlock(addr, size)

    def deallocate_block(self, block: MemoryBlock) -> None:
        self._raw.deallocate_node(block.addr, block.size, self._alignment)


class SingleBlockAllocator(_BlockAllocatorBase):
    """Hands out exactly one block, ever (reference single_block_allocator)."""

    def __init__(self, raw_allocator, block_size: int, alignment: int = 0):
        super().__init__(raw_allocator, block_size, alignment)
        self._used = False

    def allocate_block(self) -> MemoryBlock:
        if self._used:
            raise OutOfMemory(type(self).__name__, self._block_size,
                              "(single block already allocated)")
        self._used = True
        return self._make_block(self._block_size)

    def deallocate_block(self, block: MemoryBlock) -> None:
        super().deallocate_block(block)
        self._used = False


class FixedSizeBlockAllocator(_BlockAllocatorBase):
    """Unbounded supply of same-size blocks (reference fixed_size_block_allocator)."""

    def allocate_block(self) -> MemoryBlock:
        return self._make_block(self._block_size)


class GrowingBlockAllocator(_BlockAllocatorBase):
    """Each block is ``growth_factor``x the previous (reference growing_block_allocator)."""

    def __init__(self, raw_allocator, block_size: int, growth_factor: float = 2.0,
                 alignment: int = 0):
        super().__init__(raw_allocator, block_size, alignment)
        if growth_factor < 1.0:
            raise ValueError("growth_factor must be >= 1")
        self._growth = growth_factor

    def allocate_block(self) -> MemoryBlock:
        block = self._make_block(self._block_size)
        self._block_size = int(self._block_size * self._growth)
        return block


class CountLimitedBlockAllocator:
    """Caps the number of live blocks (reference count-limited composition)."""

    def __init__(self, inner, max_blocks: int):
        self._inner = inner
        self._max = max_blocks
        self._live = 0

    @property
    def memory_type(self) -> MemoryType:
        return self._inner.memory_type

    @property
    def next_block_size(self) -> int:
        return self._inner.next_block_size

    @property
    def block_count(self) -> int:
        return self._live

    def allocate_block(self) -> MemoryBlock:
        if self._live >= self._max:
            raise OutOfMemory(type(self).__name__, self._inner.next_block_size,
                              f"(block count limit {self._max} reached)")
        block = self._inner.allocate_block()
        self._live += 1
        return block

    def deallocate_block(self, block: MemoryBlock) -> None:
        self._inner.deallocate_block(block)
        self._live -= 1


class SizeLimitedBlockAllocator:
    """Caps the total bytes of live blocks (reference size-limited composition)."""

    def __init__(self, inner, max_bytes: int):
        self._inner = inner
        self._max = max_bytes
        self._bytes = 0

    @property
    def memory_type(self) -> MemoryType:
        return self._inner.memory_type

    @property
    def next_block_size(self) -> int:
        return self._inner.next_block_size

    @property
    def allocated_bytes(self) -> int:
        return self._bytes

    def allocate_block(self) -> MemoryBlock:
        size = self._inner.next_block_size
        if self._bytes + size > self._max:
            raise OutOfMemory(type(self).__name__, size,
                              f"(size limit {self._max} bytes reached, {self._bytes} in use)")
        block = self._inner.allocate_block()
        self._bytes += block.size
        return block

    def deallocate_block(self, block: MemoryBlock) -> None:
        self._inner.deallocate_block(block)
        self._bytes -= block.size
