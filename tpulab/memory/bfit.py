"""Best-fit allocator for long-lived variable-size allocations
(reference bfit_allocator.h:20-123).

Maintains free nodes ordered by size (for best-fit search) and by address (for
coalescing on free) — the Python analog of the reference's twin
``memory_node_compare_size`` / ``memory_node_compare_addr`` ordered sets.
Intended for weights/executable artifacts: allocations live long, sizes vary,
fragmentation matters more than per-op cost.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from tpulab.memory.arena import BlockArena
from tpulab.memory.debugging import InvalidPointer, OutOfMemory
from tpulab.memory.literals import align_up
from tpulab.memory.memory_type import MemoryType


class BFitAllocator:
    """Best-fit free-block allocator (reference bfit_allocator)."""

    is_stateful = True

    def __init__(self, block_allocator, grow_on_demand: bool = True):
        self._arena = (block_allocator if isinstance(block_allocator, BlockArena)
                       else BlockArena(block_allocator, cached=False))
        self._grow = grow_on_demand
        # free list: sorted by (size, addr) for best-fit; plus addr-sorted
        self._free_by_size: List[Tuple[int, int]] = []   # (size, addr)
        self._free_by_addr: List[int] = []               # addrs
        self._free_sizes: Dict[int, int] = {}            # addr -> size
        self._live: Dict[int, int] = {}                  # addr -> size
        self._blocks = []

    @property
    def memory_type(self) -> MemoryType:
        return self._arena.memory_type

    @property
    def free_bytes(self) -> int:
        return sum(self._free_sizes.values())

    @property
    def live_allocations(self) -> int:
        return len(self._live)

    # -- free-list maintenance ---------------------------------------------
    def _insert_free(self, addr: int, size: int) -> None:
        # coalesce with predecessor / successor (address-ordered)
        i = bisect.bisect_left(self._free_by_addr, addr)
        if i > 0:
            prev = self._free_by_addr[i - 1]
            if prev + self._free_sizes[prev] == addr:
                addr, size = prev, self._free_sizes[prev] + size
                self._remove_free(prev)
                i = bisect.bisect_left(self._free_by_addr, addr)
        if i < len(self._free_by_addr):
            nxt = self._free_by_addr[i]
            if addr + size == nxt:
                size += self._free_sizes[nxt]
                self._remove_free(nxt)
        bisect.insort(self._free_by_addr, addr)
        bisect.insort(self._free_by_size, (size, addr))
        self._free_sizes[addr] = size

    def _remove_free(self, addr: int) -> None:
        size = self._free_sizes.pop(addr)
        self._free_by_addr.remove(addr)
        self._free_by_size.remove((size, addr))

    # -- RawAllocator concept ----------------------------------------------
    def allocate_node(self, size: int, alignment: int = 8) -> int:
        if size <= 0:
            raise OutOfMemory("BFitAllocator", size, "(non-positive)")
        addr = self._best_fit(size, alignment)
        if addr is None and self._grow:
            block = self._arena.allocate_block()
            self._blocks.append(block)
            self._insert_free(block.addr, block.size)
            addr = self._best_fit(size, alignment)
        if addr is None:
            raise OutOfMemory("BFitAllocator", size,
                              f"(free={self.free_bytes} fragmented or exhausted)")
        return addr

    def _best_fit(self, size: int, alignment: int) -> Optional[int]:
        i = bisect.bisect_left(self._free_by_size, (size, 0))
        while i < len(self._free_by_size):
            fsize, faddr = self._free_by_size[i]
            start = align_up(faddr, alignment)
            pad = start - faddr
            if fsize >= pad + size:
                self._remove_free(faddr)
                if pad:
                    self._insert_free(faddr, pad)
                rem = fsize - pad - size
                if rem:
                    self._insert_free(start + size, rem)
                self._live[start] = size
                return start
            i += 1
        return None

    def deallocate_node(self, addr: int, size: int = 0, alignment: int = 0) -> None:
        live = self._live.pop(addr, None)
        if live is None:
            raise InvalidPointer(f"0x{addr:x} is not live in BFitAllocator")
        self._insert_free(addr, live)

    def view(self, addr: int, size: int):
        from tpulab.memory.descriptor import host_view
        return host_view(addr, size)
