"""Error taxonomy + debug handler hooks (reference debugging.h:17-97, error.h:25-267)."""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

log = logging.getLogger("tpulab.memory")


class MemoryError_(Exception):
    """Base of the allocator error taxonomy (reference error.h)."""


class OutOfMemory(MemoryError_):
    """Allocator cannot satisfy the request (reference error.h out_of_memory)."""

    def __init__(self, allocator: str, size: int, detail: str = ""):
        self.allocator = allocator
        self.size = size
        super().__init__(f"{allocator}: out of memory allocating {size} bytes {detail}".rstrip())


class BadAllocationSize(MemoryError_):
    """Request exceeds what the allocator supports (reference bad_allocation_size)."""

    def __init__(self, allocator: str, size: int, supported: int):
        self.allocator = allocator
        self.size = size
        self.supported = supported
        super().__init__(
            f"{allocator}: bad allocation size {size} (max supported {supported})")


class LeakError(MemoryError_):
    """Raised by the default leak handler when leaks are fatal."""


class InvalidPointer(MemoryError_):
    """Deallocation of a pointer the allocator does not own."""


# ---------------------------------------------------------------------------
# Handler hooks (reference debugging.h leak/invalid-pointer handler functions).
# ---------------------------------------------------------------------------

LeakHandler = Callable[[str, int], None]

_handler_lock = threading.Lock()


def _default_leak_handler(allocator: str, leaked_bytes: int) -> None:
    log.error("LEAK: allocator %s leaked %d bytes", allocator, leaked_bytes)


_leak_handler: LeakHandler = _default_leak_handler


def set_leak_handler(handler: Optional[LeakHandler]) -> LeakHandler:
    """Install a leak handler; returns the previous one (reference set_leak_handler)."""
    global _leak_handler
    with _handler_lock:
        old = _leak_handler
        _leak_handler = handler or _default_leak_handler
        return old


def get_leak_handler() -> LeakHandler:
    with _handler_lock:
        return _leak_handler


def report_leak(allocator: str, leaked_bytes: int) -> None:
    get_leak_handler()(allocator, leaked_bytes)
