"""Transactional allocator — rotating ref-counted bump stacks.

The serving-critical allocator (reference transactional_allocator.h:155-367):
per-request tensor scratch is bump-allocated in O(1) from the current stack
(reference allocate_node:207-234); when a stack can't satisfy a request the
allocator *rotates* to a fresh stack from the arena (rotate:222-227); each
allocation holds a reference on its stack and the whole stack is returned to
the arena when its last allocation drops (release_stack:305-316).  Allocation
is O(1), deallocation is O(1), and freed memory returns in whole blocks —
ideal for the per-request descriptor churn of an inference service.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from tpulab.memory.arena import BlockArena
from tpulab.memory.block import MemoryBlock
from tpulab.memory.debugging import BadAllocationSize, InvalidPointer, OutOfMemory
from tpulab.memory.descriptor import Descriptor, host_view
from tpulab.memory.literals import align_up
from tpulab.memory.memory_type import MemoryType


class _RefCountedStack:
    """One bump stack over one arena block (reference ref_counted_stack)."""

    __slots__ = ("block", "cursor", "refs", "retired")

    def __init__(self, block: MemoryBlock):
        self.block = block
        self.cursor = 0
        self.refs = 0
        self.retired = False

    def try_allocate(self, size: int, alignment: int) -> Optional[int]:
        start = align_up(self.block.addr + self.cursor, alignment) - self.block.addr
        if start + size > self.block.size:
            return None
        self.cursor = start + size
        self.refs += 1
        return self.block.addr + start

    @property
    def available(self) -> int:
        return self.block.size - self.cursor


class TransactionalAllocator:
    """Rotating ref-counted stack allocator
    (reference transactional_allocator.h:155-367).

    RawAllocator concept over any block arena; also usable directly as an
    IAllocator-style descriptor factory via :meth:`allocate_descriptor`.
    """

    is_stateful = True

    def __init__(self, block_allocator, max_stacks: int = 0):
        self._arena = (block_allocator if isinstance(block_allocator, BlockArena)
                       else BlockArena(block_allocator, cached=True))
        self._lock = threading.Lock()
        self._current: Optional[_RefCountedStack] = None
        #: addr -> owning stack, for deallocate lookups
        self._by_addr: Dict[int, _RefCountedStack] = {}
        self._stacks: List[_RefCountedStack] = []
        self._max_stacks = max_stacks  # 0 = unbounded (arena may still bound)

    @property
    def memory_type(self) -> MemoryType:
        return self._arena.memory_type

    @property
    def live_stacks(self) -> int:
        return len(self._stacks)

    def max_node_size(self) -> int:
        return self._arena.next_block_size

    # -- internals ----------------------------------------------------------
    def _rotate(self) -> _RefCountedStack:
        """Retire the current stack and pull a fresh block (reference rotate:222-227)."""
        if self._current is not None:
            self._current.retired = True
            if self._current.refs == 0:
                self._release_stack(self._current)
        if self._max_stacks and len(self._stacks) >= self._max_stacks:
            raise OutOfMemory("TransactionalAllocator", self._arena.next_block_size,
                              f"(stack limit {self._max_stacks} reached; "
                              f"{len(self._stacks)} stacks still referenced)")
        block = self._arena.allocate_block()
        stack = _RefCountedStack(block)
        self._stacks.append(stack)
        self._current = stack
        return stack

    def _release_stack(self, stack: _RefCountedStack) -> None:
        """Return a drained stack's block to the arena (reference drop:305-316)."""
        self._stacks.remove(stack)
        self._arena.deallocate_block(stack.block)
        if self._current is stack:
            self._current = None

    # -- RawAllocator concept ----------------------------------------------
    def allocate_node(self, size: int, alignment: int = 8) -> int:
        if size <= 0:
            raise BadAllocationSize("TransactionalAllocator", size, self._arena.next_block_size)
        if size > self._arena.next_block_size:
            raise BadAllocationSize("TransactionalAllocator", size,
                                    self._arena.next_block_size)
        with self._lock:
            stack = self._current
            addr = stack.try_allocate(size, alignment) if stack and not stack.retired else None
            if addr is None:
                stack = self._rotate()
                addr = stack.try_allocate(size, alignment)
                if addr is None:
                    raise BadAllocationSize("TransactionalAllocator", size,
                                            stack.block.size)
            self._by_addr[addr] = stack
            return addr

    def deallocate_node(self, addr: int, size: int = 0, alignment: int = 0) -> None:
        with self._lock:
            stack = self._by_addr.pop(addr, None)
            if stack is None:
                raise InvalidPointer(f"0x{addr:x} was not allocated here")
            stack.refs -= 1
            # A stack frees only once retired (rotation happened) and drained.
            if stack.refs == 0 and (stack.retired or stack is not self._current):
                if stack is self._current:
                    self._current = None
                self._release_stack(stack)

    # -- descriptor convenience --------------------------------------------
    def allocate_descriptor(self, size: int, alignment: int = 8) -> Descriptor:
        addr = self.allocate_node(size, alignment)
        return Descriptor(addr, size, None, alignment=alignment,
                          on_release=lambda a, s: self.deallocate_node(a, s))

    def view(self, addr: int, size: int):
        return host_view(addr, size)

    def shrink_to_fit(self) -> int:
        with self._lock:
            return self._arena.shrink_to_fit()


def make_transactional_allocator(block_allocator) -> TransactionalAllocator:
    """Reference ``make_transactional_allocator``."""
    return TransactionalAllocator(block_allocator)
