"""Owning descriptors + the type-erased allocator interface.

- ``Descriptor``: a move-only owning handle {addr, size, alignment, memory_type}
  holding a reference to its ``IAllocator``; releases the memory back on
  ``release()``/``close()``/GC (reference descriptor.h:40-99, descriptor.cc).
- ``IAllocator``: the type-erased allocator interface every concrete allocator
  facade implements: allocate / deallocate / allocate_descriptor /
  max_alignment / device_context (reference descriptor.h:102-124).

Host-accessible descriptors expose zero-copy views: ``memoryview()`` and
``numpy(dtype, shape)`` aliasing the underlying storage — the staging-buffer
path the engine layer uses to avoid copies on host->HBM transfer.  Device
(TPU/HBM) descriptors instead carry an opaque ``device_buffer`` (a JAX array).
"""

from __future__ import annotations

import abc
import ctypes
import threading
import weakref
from typing import Any, Optional

import numpy as np

from tpulab.memory.memory_type import AnyMemory, DLDeviceType, MemoryType
from tpulab.memory.debugging import InvalidPointer


def host_view(addr: int, size: int) -> memoryview:
    """Zero-copy memoryview over raw host memory [addr, addr+size)."""
    return memoryview((ctypes.c_char * size).from_address(addr)).cast("B")


class IAllocator(abc.ABC):
    """Type-erased allocator interface (reference descriptor.h:102-124)."""

    #: MemoryType of allocations from this allocator.
    memory_type: MemoryType = AnyMemory

    @abc.abstractmethod
    def allocate(self, size: int, alignment: int = 0) -> int:
        """Allocate ``size`` bytes; returns an address (opaque int for device)."""

    @abc.abstractmethod
    def deallocate(self, addr: int, size: int, alignment: int = 0) -> None:
        """Return an allocation."""

    def allocate_descriptor(self, size: int, alignment: int = 0) -> "Descriptor":
        addr = self.allocate(size, alignment)
        return Descriptor(addr, size, self, alignment=alignment or self.max_alignment())

    def max_alignment(self) -> int:
        return self.memory_type.access_alignment

    def device_context(self) -> tuple[DLDeviceType, int]:
        """DLPack-style (device_type, device_id) (reference iallocator::device_context)."""
        return (self.memory_type.device_type, 0)

    # Host access -----------------------------------------------------------
    def view(self, addr: int, size: int) -> memoryview:
        """A zero-copy memoryview over [addr, addr+size) for host-accessible kinds."""
        if not self.memory_type.host_accessible:
            raise TypeError(f"{self.memory_type} is not host accessible")
        return host_view(addr, size)


class Descriptor:
    """Move-only owning memory handle (reference descriptor.h:40-99).

    The C++ original is move-only with a shared-ptr conversion; the Python
    equivalents: descriptors are not copyable, ``release()`` detaches and
    frees, ``share()`` converts to a refcounted shared handle, and an optional
    ``on_release`` callback lets pool/transactional allocators hook returns.
    """

    __slots__ = ("_addr", "_size", "_alignment", "_allocator", "_on_release",
                 "_released", "_device_buffer", "_finalized_evt", "__weakref__")

    def __init__(self, addr: int, size: int, allocator: Optional[IAllocator],
                 alignment: int = 8, on_release=None, device_buffer: Any = None):
        self._addr = addr
        self._size = size
        self._alignment = alignment
        self._allocator = allocator
        self._on_release = on_release
        self._released = False
        self._device_buffer = device_buffer
        if allocator is not None or on_release is not None:
            weakref.finalize(self, Descriptor._finalize, allocator, addr, size,
                             alignment, on_release,
                             finalized := threading.Event())
            self._finalized_evt = finalized

    # -- identity -----------------------------------------------------------
    @property
    def addr(self) -> int:
        self._check_live()
        return self._addr

    @property
    def size(self) -> int:
        return self._size

    @property
    def alignment(self) -> int:
        return self._alignment

    @property
    def memory_type(self) -> MemoryType:
        return self._allocator.memory_type if self._allocator else AnyMemory

    @property
    def device_buffer(self) -> Any:
        """The backing device object (JAX array) for non-host kinds."""
        return self._device_buffer

    # -- lifetime -----------------------------------------------------------
    @staticmethod
    def _finalize(allocator, addr, size, alignment, on_release, evt) -> None:
        if evt.is_set():
            return
        evt.set()
        if on_release is not None:
            on_release(addr, size)
        elif allocator is not None:
            allocator.deallocate(addr, size, alignment)

    def release(self) -> None:
        """Free now (reference descriptor::release)."""
        if self._released:
            return
        self._released = True
        if hasattr(self, "_finalized_evt"):
            Descriptor._finalize(self._allocator, self._addr, self._size,
                                 self._alignment, self._on_release,
                                 self._finalized_evt)
        self._device_buffer = None

    close = release

    def detach(self) -> tuple[int, int]:
        """Give up ownership without freeing; returns (addr, size)."""
        self._check_live()
        self._released = True
        if hasattr(self, "_finalized_evt"):
            self._finalized_evt.set()
        return self._addr, self._size

    def share(self) -> "SharedDescriptor":
        """Convert to a refcounted shared handle (reference make_shared())."""
        shared = SharedDescriptor(self)
        return shared

    def _check_live(self) -> None:
        if self._released:
            raise InvalidPointer("descriptor already released")

    def __enter__(self) -> "Descriptor":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- host access --------------------------------------------------------
    def memoryview(self) -> memoryview:
        self._check_live()
        if self._allocator is None:
            return host_view(self._addr, self._size)
        return self._allocator.view(self._addr, self._size)

    def numpy(self, dtype=np.uint8, shape=None) -> np.ndarray:
        """Zero-copy numpy array aliasing this descriptor's memory."""
        arr = np.frombuffer(self.memoryview(), dtype=dtype)
        if shape is not None:
            arr = arr.reshape(shape)
        return arr

    def __len__(self) -> int:
        return self._size

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        state = "released" if self._released else f"addr=0x{self._addr:x}"
        return f"Descriptor({state}, size={self._size}, type={self.memory_type.name})"


class SharedDescriptor:
    """Refcounted wrapper over a Descriptor (reference descriptor::make_shared).

    Cheap to copy via ``ref()``; underlying memory is released when the last
    reference drops.
    """

    def __init__(self, descriptor: Descriptor):
        self._descriptor = descriptor
        self._lock = threading.Lock()
        self._refs = 1

    def ref(self) -> "SharedDescriptor":
        with self._lock:
            self._refs += 1
        return self

    def unref(self) -> None:
        with self._lock:
            self._refs -= 1
            last = self._refs == 0
        if last:
            self._descriptor.release()

    @property
    def descriptor(self) -> Descriptor:
        return self._descriptor

    def __getattr__(self, item):
        return getattr(self._descriptor, item)
