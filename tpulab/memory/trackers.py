"""Allocation trackers (reference trackers.h:10-31, tracking.h:23-170).

Instrumentation layered onto any allocator: ``SizeTracker`` counts live/total
bytes; ``TrackedBlockAllocator`` observes block traffic; both export their
gauges to :mod:`tpulab.utils.metrics` when attached.
"""

from __future__ import annotations

import threading
from typing import Optional


class SizeTracker:
    """Byte counter wrapper over a RawAllocator (reference size_tracker)."""

    def __init__(self, inner, name: str = "size_tracker"):
        self._inner = inner
        self.name = name
        self._lock = threading.Lock()
        self.bytes_in_use = 0
        self.peak_bytes = 0
        self.total_allocations = 0
        self.total_bytes = 0

    @property
    def memory_type(self):
        return self._inner.memory_type

    @property
    def is_stateful(self):
        return True

    def allocate_node(self, size: int, alignment: int = 8) -> int:
        addr = self._inner.allocate_node(size, alignment)
        with self._lock:
            self.bytes_in_use += size
            self.total_bytes += size
            self.total_allocations += 1
            self.peak_bytes = max(self.peak_bytes, self.bytes_in_use)
        return addr

    def deallocate_node(self, addr: int, size: int, alignment: int = 8) -> None:
        self._inner.deallocate_node(addr, size, alignment)
        with self._lock:
            self.bytes_in_use -= size

    def view(self, addr: int, size: int):
        return self._inner.view(addr, size)

    def max_node_size(self) -> int:
        fn = getattr(self._inner, "max_node_size", None)
        return fn() if callable(fn) else (1 << 48)


class TrackedBlockAllocator:
    """Block-traffic observer (reference tracked_block_allocator /
    deeply_tracked_block_allocator)."""

    def __init__(self, inner, on_allocate=None, on_deallocate=None):
        self._inner = inner
        self._on_alloc = on_allocate
        self._on_dealloc = on_deallocate
        self.blocks_allocated = 0
        self.blocks_deallocated = 0
        self.bytes_in_use = 0

    @property
    def memory_type(self):
        return self._inner.memory_type

    @property
    def next_block_size(self):
        return self._inner.next_block_size

    def allocate_block(self):
        block = self._inner.allocate_block()
        self.blocks_allocated += 1
        self.bytes_in_use += block.size
        if self._on_alloc:
            self._on_alloc(block)
        return block

    def deallocate_block(self, block) -> None:
        self._inner.deallocate_block(block)
        self.blocks_deallocated += 1
        self.bytes_in_use -= block.size
        if self._on_dealloc:
            self._on_dealloc(block)
