"""Fixed-node memory pool over a free list (reference memory_pool.h:37-295,
detail/free_list.h).

Carves blocks from a block arena into fixed-size nodes kept on a LIFO free
list; ``allocate_node`` pops, ``deallocate_node`` pushes.  Array variant
allocates N contiguous nodes.  Leak checking on destruction mirrors the
reference's leak-checker policy (memory_pool.h:27-33).
"""

from __future__ import annotations

from typing import List, Set

from tpulab.memory.arena import BlockArena
from tpulab.memory.block import MemoryBlock
from tpulab.memory.debugging import (InvalidPointer, OutOfMemory, report_leak)
from tpulab.memory.literals import align_up
from tpulab.memory.memory_type import MemoryType


class MemoryPool:
    """Fixed-node-size pool (reference node_pool / array_pool)."""

    def __init__(self, node_size: int, block_allocator, alignment: int = 8,
                 leak_check: bool = True):
        if node_size <= 0:
            raise ValueError("node_size must be positive")
        self._node_size = align_up(node_size, alignment)
        self._alignment = alignment
        self._arena = (block_allocator if isinstance(block_allocator, BlockArena)
                       else BlockArena(block_allocator, cached=True))
        self._free: List[int] = []
        self._blocks: List[MemoryBlock] = []
        self._live: Set[int] = set()
        self._leak_check = leak_check

    @property
    def memory_type(self) -> MemoryType:
        return self._arena.memory_type

    @property
    def node_size(self) -> int:
        return self._node_size

    @property
    def free_nodes(self) -> int:
        return len(self._free)

    @property
    def capacity(self) -> int:
        return len(self._free) + len(self._live)

    def _grow(self) -> None:
        block = self._arena.allocate_block()
        self._blocks.append(block)
        addr = align_up(block.addr, self._alignment)
        end = block.addr + block.size
        while addr + self._node_size <= end:
            self._free.append(addr)
            addr += self._node_size

    # RawAllocator concept --------------------------------------------------
    def allocate_node(self, size: int = 0, alignment: int = 0) -> int:
        size = size or self._node_size
        if size > self._node_size:
            raise OutOfMemory("MemoryPool", size,
                              f"(node size is {self._node_size})")
        if not self._free:
            self._grow()
            if not self._free:
                raise OutOfMemory("MemoryPool", size, "(block too small for one node)")
        addr = self._free.pop()
        self._live.add(addr)
        return addr

    def deallocate_node(self, addr: int, size: int = 0, alignment: int = 0) -> None:
        if addr not in self._live:
            raise InvalidPointer(f"0x{addr:x} is not a live node of this pool")
        self._live.remove(addr)
        self._free.append(addr)

    def allocate_array(self, count: int) -> int:
        """N contiguous nodes (reference array_pool).  Scans the free list."""
        if count <= 0:
            raise ValueError("count must be positive")
        if count == 1:
            return self.allocate_node()
        runs = self._find_run(count)
        if runs is None:
            self._grow()
            runs = self._find_run(count)
        if runs is None:
            raise OutOfMemory("MemoryPool", count * self._node_size,
                              f"(no contiguous run of {count} nodes)")
        for a in runs:
            self._free.remove(a)
            self._live.add(a)
        return runs[0]

    def deallocate_array(self, addr: int, count: int) -> None:
        for i in range(count):
            self.deallocate_node(addr + i * self._node_size)

    def _find_run(self, count: int):
        free_sorted = sorted(self._free)
        run = [free_sorted[0]] if free_sorted else []
        for a in free_sorted[1:]:
            if run and a == run[-1] + self._node_size:
                run.append(a)
            else:
                run = [a]
            if len(run) == count:
                return run
        return None

    def close(self) -> None:
        if self._leak_check and self._live:
            report_leak("MemoryPool", len(self._live) * self._node_size)
        self._live.clear()
        self._free.clear()
        for block in self._blocks:
            self._arena.deallocate_block(block)
        self._blocks.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
