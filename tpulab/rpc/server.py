"""Server + service registration (reference server.h:40-89, service.h:35-61,
rpc.h:35-73, executor.cc progress engines).

grpc-python owns the completion-queue progress engines, so this layer keeps
the reference's *surface* — ``Server``, ``AsyncService.register_rpc``,
executors, pre-request contexts — and wires it through gRPC generic method
handlers (no codegen plugin needed; message classes come from protoc).

Lifecycle mapping:
- ``Executor`` (threads) -> ``grpc.server`` with a worker pool and
  ``maximum_concurrent_rpcs`` as the pre-armed-context bound
- ``FiberExecutor`` -> ``grpc.aio`` server on a dedicated event-loop thread;
  context bodies may be coroutines (handlers await without costing threads)
- ``Server.run(control_fn, control_period_s)`` runs a periodic control lambda
  exactly like the reference's NVML power-gauge loop (server.cc:322-331)
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import queue as _queue
import threading
import time
from concurrent import futures as _futures
from typing import Any, Callable, Dict, List, Optional, Type

import grpc

from tpulab.core.dispatcher import AsyncDispatcher, Dispatcher
from tpulab.core.resources import Resources
from tpulab.rpc.context import BatchingContext, Context, StreamingContext
from tpulab.rpc.executor import Executor, FiberExecutor

log = logging.getLogger("tpulab.rpc")

_STREAM_END = object()


class _RPCDef:
    def __init__(self, name, context_cls, req_des, resp_ser, resources):
        self.name = name
        self.context_cls = context_cls
        self.req_des = req_des
        self.resp_ser = resp_ser
        self.resources = resources
        self.dispatcher = None  # built for BatchingContext at server start
        # pre-armed context free-list (reference pre-allocated contexts,
        # executor.cc:48-67): unary contexts recycle through here instead
        # of re-instantiating per call.  Streaming/batching contexts carry
        # per-stream state and are never pooled.
        self.ctx_pool: List[Any] = []
        self.ctx_pool_lock = threading.Lock()
        self.ctx_pool_cap = 0  # set at server start from the executor

    def acquire_context(self):
        with self.ctx_pool_lock:
            if self.ctx_pool:
                return self.ctx_pool.pop()
        ctx = self.context_cls(self.resources)
        # reuse contract: anything set during execute_rpc is per-request
        # state and is stripped on release; only construction-time
        # attributes survive recycling (so a pooled context looks freshly
        # constructed to the next — possibly different — client).
        ctx._pool_baseline = frozenset(ctx.__dict__) | {"_pool_baseline"}
        return ctx

    def release_context(self, ctx) -> None:
        ctx.grpc_context = None
        baseline = getattr(ctx, "_pool_baseline", None)
        if baseline is not None:
            for attr in [k for k in ctx.__dict__ if k not in baseline]:
                del ctx.__dict__[attr]
        with self.ctx_pool_lock:
            if len(self.ctx_pool) < self.ctx_pool_cap:
                self.ctx_pool.append(ctx)


class AsyncService:
    """Named service: a method table of RPC name -> Context class
    (reference AsyncService::RegisterRPC)."""

    def __init__(self, name: str, resources: Optional[Resources] = None):
        self.name = name
        self.resources = resources
        self._rpcs: Dict[str, _RPCDef] = {}

    def register_rpc(self, method: str, context_cls: Type,
                     request_deserializer: Callable[[bytes], Any] = None,
                     response_serializer: Callable[[Any], bytes] = None,
                     resources: Optional[Resources] = None) -> None:
        """Bind an RPC method to its per-request Context class."""
        self._rpcs[method] = _RPCDef(
            method, context_cls,
            request_deserializer or (lambda b: b),
            response_serializer or (lambda m: m if isinstance(m, bytes) else bytes(m)),
            resources or self.resources)

    @property
    def rpcs(self) -> Dict[str, _RPCDef]:
        return self._rpcs


class Server:
    """gRPC server owning services + executors (reference Server)."""

    def __init__(self, address: str = "0.0.0.0:50051",
                 executor: Optional[Executor | FiberExecutor] = None):
        self.address = address
        self.executor = executor or Executor()
        self._services: List[AsyncService] = []
        self._server = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._stop = threading.Event()
        self._worker_pool: Optional[_futures.ThreadPoolExecutor] = None
        self.bound_port: Optional[int] = None

    # -- registration --------------------------------------------------------
    def register_async_service(self, service: AsyncService) -> AsyncService:
        self._services.append(service)
        return service

    def register_executor(self, executor) -> None:
        """Reference parity: swap the execution domain before start."""
        self.executor = executor

    # -- lifecycle ------------------------------------------------------------
    def async_start(self) -> None:
        """Start serving without blocking (reference AsyncStart)."""
        if self.executor.is_fiber:
            self._start_aio()
        else:
            self._start_sync()
        self._running.set()

    def run(self, control_fn: Optional[Callable[[], None]] = None,
            control_period_s: float = 2.0) -> None:
        """Serve until shutdown; runs control_fn every period
        (reference Server::Run(timeout, control_fn))."""
        if not self._running.is_set():  # idempotent after async_start()
            self.async_start()
        try:
            while not self._stop.wait(timeout=control_period_s):
                if control_fn is not None:
                    try:
                        control_fn()
                    except Exception:
                        log.exception("control lambda failed")
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self, grace_s: float = 2.0) -> None:
        self._stop.set()
        if self._server is None:
            return
        if self.executor.is_fiber:
            async def _stop_server():
                await self._server.stop(grace_s)
            fut = asyncio.run_coroutine_threadsafe(_stop_server(), self._loop)
            fut.result(timeout=grace_s + 5)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=5)
        else:
            self._server.stop(grace_s).wait(timeout=grace_s + 5)
        # release execution resources the server created (reference
        # Executor/ThreadPool teardown on Shutdown)
        for service in self._services:
            for rpc in service.rpcs.values():
                if rpc.dispatcher is not None:
                    if hasattr(rpc.dispatcher, "shutdown"):
                        rpc.dispatcher.shutdown()
                    rpc.dispatcher = None
        if self._worker_pool is not None:
            self._worker_pool.shutdown(wait=False)
            self._worker_pool = None
        # attached service resources (e.g. infer_service's batched runners /
        # generate workers) are owned by the server lifecycle
        res = getattr(self, "_infer_resources", None)
        if res is not None and hasattr(res, "shutdown"):
            try:
                res.shutdown()
            except Exception:  # pragma: no cover
                log.exception("service resources shutdown failed")
        self._server = None
        self._running.clear()

    def wait_until_running(self, timeout: float = 10.0) -> None:
        if not self._running.wait(timeout):
            raise TimeoutError("server did not start")

    # -- sync (thread Executor) ----------------------------------------------
    def _start_sync(self) -> None:
        ex = self.executor
        # the executor OWNS the worker pool: sizing to the pre-armed-context
        # bound (reference contexts_per_thread) and pinning each worker to
        # the executor's cpu plan (reference CQ-thread affinity)
        pool = ex.build_worker_pool()
        self._worker_pool = pool
        self._server = grpc.server(
            pool, maximum_concurrent_rpcs=ex.max_concurrency)
        for service in self._services:
            handlers = {}
            for rpc in service.rpcs.values():
                rpc.ctx_pool_cap = min(ex.max_concurrency, 256)
                handlers[rpc.name] = self._make_sync_handler(rpc)
            self._server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(service.name, handlers),))
        self.bound_port = self._server.add_insecure_port(self.address)
        self._server.start()

    def _make_sync_handler(self, rpc: _RPCDef):
        if issubclass(rpc.context_cls, StreamingContext):
            def stream_behavior(request_iterator, grpc_ctx):
                ctx = rpc.context_cls(rpc.resources)
                ctx.grpc_context = grpc_ctx
                out: _queue.Queue = _queue.Queue()
                ctx.write = out.put
                ctx.on_stream_initialized()

                errors: List[BaseException] = []

                def reader():
                    try:
                        for req in request_iterator:
                            ctx.on_request(req)
                        ctx.on_requests_finished()
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                    finally:
                        out.put(_STREAM_END)

                t = threading.Thread(target=reader, daemon=True)
                t.start()
                while True:
                    item = out.get()
                    if item is _STREAM_END:
                        break
                    yield item
                t.join()
                if errors:
                    # surface the handler failure as a stream error instead
                    # of a clean OK completion
                    grpc_ctx.abort(grpc.StatusCode.INTERNAL, str(errors[0]))
            return grpc.stream_stream_rpc_method_handler(
                stream_behavior, rpc.req_des, rpc.resp_ser)

        if issubclass(rpc.context_cls, BatchingContext):
            cls = rpc.context_cls

            def execute(items, complete):
                ctx = cls(rpc.resources)
                responses = ctx.execute_batch([it["request"] for it in items])
                for it, resp in zip(items, responses):
                    it["response"] = resp
                complete(None)

            rpc.dispatcher = Dispatcher(
                max_batch_size=cls.max_batch_size,
                window_s=cls.batch_window_s,
                execute_fn=execute, n_workers=2)

            def batch_behavior(request, grpc_ctx):
                item = {"request": request}
                rpc.dispatcher.enqueue(item).result()
                return item["response"]
            return grpc.unary_unary_rpc_method_handler(
                batch_behavior, rpc.req_des, rpc.resp_ser)

        def unary_behavior(request, grpc_ctx):
            ctx = rpc.acquire_context()   # pre-armed context free-list
            ctx.grpc_context = grpc_ctx
            ctx.on_lifecycle_start()
            try:
                return ctx.execute_rpc(request)
            finally:
                ctx.on_lifecycle_reset()
                rpc.release_context(ctx)
        return grpc.unary_unary_rpc_method_handler(
            unary_behavior, rpc.req_des, rpc.resp_ser)

    # -- aio (FiberExecutor) ---------------------------------------------------
    def _start_aio(self) -> None:
        started = threading.Event()
        startup_error: List[BaseException] = []

        def loop_main():
            if hasattr(self.executor, "pin_loop_thread"):
                self.executor.pin_loop_thread()  # reference thread affinity
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def boot():
                server = grpc.aio.server(
                    maximum_concurrent_rpcs=self.executor.max_concurrency)
                for service in self._services:
                    handlers = {}
                    for rpc in service.rpcs.values():
                        rpc.ctx_pool_cap = min(
                            self.executor.max_concurrency, 256)
                        handlers[rpc.name] = self._make_aio_handler(rpc)
                    server.add_generic_rpc_handlers(
                        (grpc.method_handlers_generic_handler(
                            service.name, handlers),))
                self.bound_port = server.add_insecure_port(self.address)
                await server.start()
                self._server = server

            try:
                loop.run_until_complete(boot())
            except BaseException as e:  # noqa: BLE001
                startup_error.append(e)
                started.set()
                return
            started.set()
            loop.run_forever()

        self._loop_thread = threading.Thread(target=loop_main, name="rpc-aio",
                                             daemon=True)
        self._loop_thread.start()
        started.wait(timeout=10)
        if startup_error:
            raise startup_error[0]

    def _make_aio_handler(self, rpc: _RPCDef):
        async def maybe_await(x):
            return await x if inspect.isawaitable(x) else x

        if issubclass(rpc.context_cls, StreamingContext):
            async def stream_behavior(request_iterator, grpc_ctx):
                ctx = rpc.context_cls(rpc.resources)
                ctx.grpc_context = grpc_ctx
                out: asyncio.Queue = asyncio.Queue()
                loop = asyncio.get_running_loop()

                def write(resp):  # thread-safe writer (reference ServerStream)
                    loop.call_soon_threadsafe(out.put_nowait, resp)
                ctx.write = write
                await maybe_await(ctx.on_stream_initialized())

                async def reader():
                    try:
                        async for req in request_iterator:
                            await maybe_await(ctx.on_request(req))
                        await maybe_await(ctx.on_requests_finished())
                    finally:
                        # always posted, and through the same scheduling path
                        # as write() so it cannot overtake earlier responses
                        loop.call_soon_threadsafe(out.put_nowait, _STREAM_END)

                task = asyncio.ensure_future(reader())
                while True:
                    item = await out.get()
                    if item is _STREAM_END:
                        break
                    yield item
                await task  # re-raises handler failures as a stream error
            return grpc.stream_stream_rpc_method_handler(
                stream_behavior, rpc.req_des, rpc.resp_ser)

        if issubclass(rpc.context_cls, BatchingContext):
            cls = rpc.context_cls

            async def execute(items, complete):
                ctx = cls(rpc.resources)
                result = ctx.execute_batch([it["request"] for it in items])
                responses = await maybe_await(result)
                for it, resp in zip(items, responses):
                    it["response"] = resp
                complete(None)

            def get_dispatcher():
                if rpc.dispatcher is None:
                    rpc.dispatcher = AsyncDispatcher(
                        max_batch_size=cls.max_batch_size,
                        window_s=cls.batch_window_s, execute_fn=execute)
                return rpc.dispatcher

            async def batch_behavior(request, grpc_ctx):
                item = {"request": request}
                await get_dispatcher().enqueue(item)
                return item["response"]
            return grpc.unary_unary_rpc_method_handler(
                batch_behavior, rpc.req_des, rpc.resp_ser)

        async def unary_behavior(request, grpc_ctx):
            ctx = rpc.acquire_context()   # pre-armed context free-list
            ctx.grpc_context = grpc_ctx
            ctx.on_lifecycle_start()
            try:
                return await maybe_await(ctx.execute_rpc(request))
            finally:
                ctx.on_lifecycle_reset()
                rpc.release_context(ctx)
        return grpc.unary_unary_rpc_method_handler(
            unary_behavior, rpc.req_des, rpc.resp_ser)
