"""tpulab.rpc — async gRPC microservice framework (reference trtlab/nvrpc,
SURVEY §2.4, ~5.9k LoC).

The reference wraps gRPC's completion-queue API in context state machines so
services are written as small classes; this build keeps that surface on
grpc-python:

- :class:`Server` — owns the grpc server, registered services and executors
  (reference server.h:40-89)
- :class:`AsyncService`/:func:`register_rpc` — method table binding RPC names
  to Context classes (reference service.h:35-61, rpc.h:35-73)
- :class:`Context` / :class:`StreamingContext` / :class:`BatchingContext` —
  per-request lifecycles (reference context.h:41-158, life_cycle_unary.h,
  life_cycle_streaming.h, life_cycle_batching.h)
- :class:`Executor` / :class:`FiberExecutor` — thread-pool vs event-loop
  execution domains (reference executor.h:39-113, fiber/executor.h:37-64).
  With FiberExecutor, context bodies are coroutines and may await pool
  readiness without stalling any OS thread — the fiber property.
- client: :class:`ClientExecutor`, :class:`ClientUnary`, streaming client
  (reference client/*.h)
- :mod:`infer_service` — the TRTIS-protocol inference service + remote
  client (reference pybind BasicInferService / PyRemoteInferenceManager)
- :mod:`replica` — client-side replica sets (:class:`ReplicaSet` unary,
  :class:`GenerationReplicaSet` token streams): least-loaded routing,
  health, exactly-once failover (SURVEY §2.8 axes 5-6 in-framework)
"""

from tpulab.rpc.context import Context, StreamingContext, BatchingContext
from tpulab.rpc.executor import Executor, FiberExecutor
from tpulab.rpc.server import Server, AsyncService
from tpulab.rpc.client import ClientExecutor, ClientUnary, ClientStreaming

__all__ = [
    "Context", "StreamingContext", "BatchingContext",
    "Executor", "FiberExecutor",
    "Server", "AsyncService",
    "ClientExecutor", "ClientUnary", "ClientStreaming",
]
