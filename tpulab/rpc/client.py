"""RPC clients (reference client/executor.h, client_unary.h:41-140,
client_streaming*.h).

- ``ClientExecutor``: channel pool with round-robin handout (reference
  client Executor GetNextCQ)
- ``ClientUnary``: async unary client — ``start(request)`` returns a future
  whose completion runs the wrapped on_complete callback (reference
  PrepareFn/StartCall + async_compute)
- ``ClientStreaming``: bidirectional stream with a background writer queue,
  read callback, and ``done()`` future (reference client_streaming v3 +
  client_single_up_multiple_down)
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

import grpc

from tpulab import chaos
from tpulab.core.async_compute import SharedPackagedTask

_WRITES_DONE = object()


def jittered_backoff_s(retry_after_ms: int, attempt: int = 0,
                       floor_s: float = 0.05, cap_s: float = 30.0,
                       jitter: float = 0.5, rng=None) -> float:
    """Client backoff honoring a server ``retry_after_ms`` hint.

    The hint (floored at ``floor_s`` when the server sent none) doubles
    per ``attempt`` and is capped; the result is then jittered uniformly
    over ``[1 - jitter, 1] × delay`` so a fleet of rejected clients
    decorrelates instead of re-arriving as the same thundering herd that
    caused the rejection (RESOURCE_EXHAUSTED contract, docs/SERVING.md).
    """
    import random
    base = max(floor_s, retry_after_ms / 1e3)
    delay = min(cap_s, base * (2.0 ** max(0, attempt)))
    r = (rng or random).random()
    return delay * (1.0 - jitter + jitter * r)


class ClientExecutor:
    """Round-robin channel pool (reference client Executor)."""

    def __init__(self, target: str, channels: int = 1,
                 options: Optional[list] = None):
        self.target = target
        self._channels: List[grpc.Channel] = [
            grpc.insecure_channel(target, options=options)
            for _ in range(max(1, channels))]
        self._rr = itertools.cycle(range(len(self._channels)))

    def channel(self) -> grpc.Channel:
        return self._channels[next(self._rr)]

    def close(self) -> None:
        for ch in self._channels:
            ch.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ClientUnary:
    """Future-returning unary client (reference ClientUnary)."""

    def __init__(self, executor: ClientExecutor, method: str,
                 request_serializer: Callable[[Any], bytes] = None,
                 response_deserializer: Callable[[bytes], Any] = None):
        self._executor = executor
        self._method = method
        self._ser = request_serializer
        self._des = response_deserializer

    def _stub(self):
        return self._executor.channel().unary_unary(
            self._method, request_serializer=self._ser,
            response_deserializer=self._des)

    def start(self, request, on_complete: Optional[Callable] = None,
              timeout: Optional[float] = None,
              metadata: Optional[list] = None) -> Future:
        """Async call; returns a future of on_complete(response) (identity
        by default).  Mirrors async_compute-wrapped completions.
        ``metadata`` rides the call as gRPC invocation metadata (e.g. the
        trace context, utils.tracing.TRACE_METADATA_KEY)."""
        task = SharedPackagedTask(on_complete or (lambda resp: resp))
        # chaos: delay/error the send, or black-hole it entirely — the
        # future then resolves only via its own timeout, exactly what a
        # dropped packet looks like to deadline/failover machinery (the
        # timer exists only on this armed test path)
        if chaos.trip("rpc.client.unary") == "drop":
            fut = task.get_future()
            if timeout is not None:
                def _expire():
                    if not fut.done():
                        fut.set_exception(TimeoutError(
                            f"chaos-dropped call timed out after {timeout}s"))
                t = threading.Timer(timeout, _expire)
                t.daemon = True
                t.start()
            return fut
        call = self._stub().future(request, timeout=timeout,
                                   metadata=metadata)

        def _done(c):
            try:
                task(c.result())
            except BaseException as e:  # noqa: BLE001
                fut = task.get_future()
                if not fut.done():
                    fut.set_exception(e)
        call.add_done_callback(_done)
        return task.get_future()

    def call(self, request, timeout: Optional[float] = None):
        """Blocking convenience."""
        return self.start(request, timeout=timeout).result(timeout)


class ClientStreaming:
    """Bidirectional streaming client (reference client_streaming v3)."""

    def __init__(self, executor: ClientExecutor, method: str,
                 on_response: Callable[[Any], None],
                 request_serializer: Callable[[Any], bytes] = None,
                 response_deserializer: Callable[[bytes], Any] = None,
                 timeout: Optional[float] = None,
                 metadata: Optional[list] = None):
        """``timeout`` sets the gRPC deadline for the WHOLE stream: the
        transport-level backstop of the application deadline (the server
        sees it via ``grpc-timeout`` metadata / ``time_remaining()``);
        ``metadata`` rides as invocation metadata (trace context)."""
        self._on_response = on_response
        self._writes: "_queue.Queue" = _queue.Queue()
        self._done: Future = Future()
        stub = executor.channel().stream_stream(
            method, request_serializer=request_serializer,
            response_deserializer=response_deserializer)

        def request_iter():
            while True:
                item = self._writes.get()
                if item is _WRITES_DONE:
                    return
                yield item

        self._call = stub(request_iter(), timeout=timeout,
                          metadata=metadata)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            for resp in self._call:
                # chaos: a mid-stream transport fault — the error tears the
                # stream down exactly like a dead replica would
                chaos.trip("rpc.client.stream_recv")
                self._on_response(resp)
            self._done.set_result(None)
        except BaseException as e:  # noqa: BLE001
            if not self._done.done():
                self._done.set_exception(e)

    def write(self, request) -> None:
        """Queue a request (reference Write; thread-safe)."""
        self._writes.put(request)

    def writes_done(self) -> None:
        """Half-close (reference WritesDone)."""
        self._writes.put(_WRITES_DONE)

    def done(self) -> Future:
        """Future resolving when the server finishes the stream."""
        return self._done

    def cancel(self) -> None:
        self._call.cancel()
        # unblock grpc's request-consumer thread: it sits in Queue.get()
        # inside request_iter and cancel alone cannot interrupt it
        self._writes.put(_WRITES_DONE)
